"""DLRM-style CTR model: dense MLP bottom + N sparse embedding features +
pairwise-dot interaction + top MLP.

Reference lineage: the reference framework's recsys heart — CTR models over
distributed lookup tables (SURVEY.md L2/L8) — in the DLRM shape (Naumov et
al.) the modern benchmarks standardized on.  One CONCATENATED table holds
every sparse feature's vocab (per-feature id offsets into it), which is what
makes the table giant and the embedding strategy the interesting choice:

  embedding="sparse"   nn.Embedding(sparse=True): single-device table,
                       RowSparseGrad lazy updates — the parity oracle.
  embedding="dense"    nn.Embedding(sparse=False): dense grads; the only
                       mode that composes with TrainStep(accum_steps>1).
  embedding="sharded"  embedding.ShardedEmbedding: rows sharded over a mesh
                       axis, per-shard lazy updates.
  embedding="external" no table parameter at all — forward takes the
                       already-gathered (B, F, D) rows, the host-resident
                       HostEmbeddingTable/HostPrefetchPipeline contract.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from .. import nn
from ..nn import functional as F
from ..tensor.linalg import matmul
from ..tensor.manipulation import concat, index_select, reshape, unsqueeze


class DLRMConfig:
    def __init__(self, dense_dim: int = 4,
                 vocab_sizes: Sequence[int] = (64, 64, 64, 64),
                 embedding_dim: int = 8,
                 bottom_mlp: Sequence[int] = (16,),
                 top_mlp: Sequence[int] = (16,)):
        self.dense_dim = int(dense_dim)
        self.vocab_sizes = tuple(int(v) for v in vocab_sizes)
        self.embedding_dim = int(embedding_dim)
        self.bottom_mlp = tuple(int(h) for h in bottom_mlp)
        self.top_mlp = tuple(int(h) for h in top_mlp)

    @property
    def num_features(self) -> int:
        return len(self.vocab_sizes)

    @property
    def total_rows(self) -> int:
        return sum(self.vocab_sizes)

    @property
    def offsets(self) -> np.ndarray:
        """Per-feature row offsets into the concatenated table."""
        return np.concatenate(
            [[0], np.cumsum(self.vocab_sizes[:-1])]).astype(np.int64)


def _mlp(sizes):
    layers = []
    for i in range(len(sizes) - 1):
        layers.append(nn.Linear(sizes[i], sizes[i + 1]))
        if i < len(sizes) - 2:
            layers.append(nn.ReLU())
    return nn.Sequential(*layers)


class DLRM(nn.Layer):
    """forward(dense_x, sparse) -> logits (B, 1).

    `sparse` is int ids of shape (B, F) for the table-owning modes, or the
    pre-gathered float rows (B, F, D) for embedding="external"."""

    def __init__(self, config: DLRMConfig, embedding: str = "sparse",
                 mesh=None, axis: str = "tp"):
        super().__init__()
        self.config = config
        self.embedding_mode = embedding
        d = config.embedding_dim
        f = config.num_features
        self.bottom = _mlp((config.dense_dim,) + config.bottom_mlp + (d,))
        if embedding == "external":
            self.table = None
        elif embedding == "sharded":
            from ..embedding import ShardedEmbedding
            self.table = ShardedEmbedding(config.total_rows, d, mesh=mesh,
                                          axis=axis)
        elif embedding in ("sparse", "dense"):
            self.table = nn.Embedding(config.total_rows, d,
                                      sparse=(embedding == "sparse"))
        else:
            raise ValueError(
                f"DLRM: unknown embedding mode {embedding!r}; expected "
                "'sparse', 'dense', 'sharded' or 'external'")
        # pairwise-dot interaction over F embeddings + the bottom output,
        # then the concatenated [bottom, upper-triangle dots] feeds the top
        self._n_vec = f + 1
        iu, ju = np.triu_indices(self._n_vec, k=1)
        self._pair_idx = (iu * self._n_vec + ju).astype(np.int64)
        top_in = d + len(iu)
        self.top = _mlp((top_in,) + config.top_mlp + (1,))

    def forward(self, dense_x, sparse):
        b = self.bottom(dense_x)                       # (B, D)
        if self.table is None:
            emb = sparse                               # (B, F, D) pre-gathered
        else:
            ids = sparse + Tensor(jnp.asarray(self.config.offsets)
                                  .reshape(1, -1))
            emb = self.table(ids)                      # (B, F, D)
        z = concat([unsqueeze(b, 1), emb], axis=1)     # (B, F+1, D)
        dots = matmul(z, z, transpose_y=True)          # (B, F+1, F+1)
        flat = reshape(dots, (-1, self._n_vec * self._n_vec))
        inter = index_select(flat, Tensor(jnp.asarray(self._pair_idx)),
                             axis=1)                   # (B, F*(F+1)/2)
        x = concat([b, inter], axis=1)
        return self.top(x)


class DLRMCriterion(nn.Layer):
    """Click-through loss: mean sigmoid BCE over the (B, 1) logits."""

    def forward(self, logits, label):
        label = Tensor(unwrap(label).astype(unwrap(logits).dtype))
        return F.binary_cross_entropy_with_logits(
            logits, label.reshape(unwrap(logits).shape))


def dlrm_tiny_config() -> DLRMConfig:
    """Test/smoke config: fits the 8-virtual-device CPU mesh."""
    return DLRMConfig(dense_dim=4, vocab_sizes=(64, 64, 64, 64),
                      embedding_dim=8, bottom_mlp=(16,), top_mlp=(16,))
