"""ERNIE model family (benchmark config #4: ERNIE-large + ZeRO sharding,
reference Fleet sharding_optimizer.py path).

Architecturally a BERT encoder with a task-type embedding and relu-gelu
configurable activation — shares the TPU-first blocks from models.bert.
"""
from __future__ import annotations

from ..nn.layer_base import Layer
from ..nn.layer.common import Linear, Dropout, Embedding
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import ParamAttr
from .bert import (BertConfig, BertModel, BertEmbeddings, BertLMHead,
                   BertPretrainingCriterion)


class ErnieConfig(BertConfig):
    def __init__(self, vocab_size=18000, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072,
                 hidden_act="relu", task_type_vocab_size=3, use_task_id=True,
                 **kw):
        super().__init__(vocab_size=vocab_size, hidden_size=hidden_size,
                         num_hidden_layers=num_hidden_layers,
                         num_attention_heads=num_attention_heads,
                         intermediate_size=intermediate_size,
                         hidden_act=hidden_act, **kw)
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id


def ernie_base_config(**kw):
    return ErnieConfig(**kw)


def ernie_large_config(**kw):
    base = dict(hidden_size=1024, num_hidden_layers=24,
                num_attention_heads=16, intermediate_size=4096)
    base.update(kw)
    return ErnieConfig(**base)


class ErnieEmbeddings(BertEmbeddings):
    def __init__(self, cfg: ErnieConfig):
        super().__init__(cfg)
        if cfg.use_task_id:
            self.task_type_embeddings = Embedding(
                cfg.task_type_vocab_size, cfg.hidden_size,
                weight_attr=ParamAttr(
                    initializer=I.Normal(0.0, cfg.initializer_range)))
        self.use_task_id = cfg.use_task_id

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        emb = self._sum_embeddings(input_ids, token_type_ids, position_ids)
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = Tensor(jnp.zeros_like(unwrap(input_ids)))
            emb = emb + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(emb))


class ErnieModel(BertModel):
    def __init__(self, cfg: ErnieConfig = None, **kw):
        super().__init__(cfg or ErnieConfig(**kw))

    def _make_embeddings(self, cfg):
        return ErnieEmbeddings(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        mask = self.make_attn_mask(input_ids, attention_mask)
        h = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        for layer in self.layers:
            h = layer(h, mask)
        return h, self.pooler(h)


class ErnieForPretraining(Layer):
    def __init__(self, cfg: ErnieConfig = None, **kw):
        super().__init__()
        self.ernie = ErnieModel(cfg, **kw)
        cfg = self.ernie.config
        self.cls = BertLMHead(cfg,
                              self.ernie.embeddings.word_embeddings.weight)
        self.nsp = Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        seq_out, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                                     attention_mask, task_type_ids)
        return self.cls(seq_out), self.nsp(pooled)


ErniePretrainingCriterion = BertPretrainingCriterion


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig = None, num_classes=2, **kw):
        super().__init__()
        self.ernie = ErnieModel(cfg, **kw)
        cfg = self.ernie.config
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask)
        return self.classifier(self.dropout(pooled))
