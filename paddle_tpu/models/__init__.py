"""flagship model zoo (bert/gpt2/ernie/resnet) — built out."""
