"""Flagship model zoo: BERT / GPT-2 / ERNIE pretraining models for the
BASELINE.md benchmark configs (#3 BERT DP, #4 ERNIE sharding, #5 GPT-2 PP)."""
from .bert import (BertConfig, BertModel, BertForPretraining,  # noqa: F401
                   BertPretrainingCriterion,
                   BertForSequenceClassification,
                   bert_base_config, bert_large_config)
from .gpt import (GPTConfig, GPTModel, GPTForPretraining,  # noqa: F401
                  GPTPretrainingCriterion, GPTBlock,
                  gpt2_small_config, gpt2_medium_config, gpt2_large_config)
from .ernie import (ErnieConfig, ErnieModel, ErnieForPretraining,  # noqa: F401
                    ErniePretrainingCriterion,
                    ErnieForSequenceClassification,
                    ernie_base_config, ernie_large_config)
from .dlrm import (DLRMConfig, DLRM, DLRMCriterion,  # noqa: F401
                   dlrm_tiny_config)
