"""GPT-2 model family (decoder-only; benchmark config #5: GPT-2 medium with
pipeline parallel + recompute, reference PipelineOptimizer + RecomputeOptimizer
paths in python/paddle/fluid/optimizer.py:3693,4491).

TPU-first: pre-LN blocks, causal flash attention (pallas), fused QKV, tied
LM head.  Blocks are written so `parallel.pipeline` can stack their params on
a leading axis and `lax.scan` over them (identical per-layer structure).
"""
from __future__ import annotations

from ..nn.layer_base import Layer, ParamAttr
from ..nn.layer.common import Linear, Dropout, Embedding
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn import initializer as I


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 hidden_act="gelu", hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1,
                 max_position_embeddings=1024, initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.initializer_range = initializer_range


def gpt2_small_config(**kw):
    return GPTConfig(**kw)


def gpt2_medium_config(**kw):
    base = dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16)
    base.update(kw)
    return GPTConfig(**base)


def gpt2_large_config(**kw):
    base = dict(hidden_size=1280, num_hidden_layers=36, num_attention_heads=20)
    base.update(kw)
    return GPTConfig(**base)


def _winit(std):
    return ParamAttr(initializer=I.Normal(0.0, std))


class GPTBlock(Layer):
    """Pre-LN transformer decoder block with fused QKV + causal flash attn."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self._remat_stage = True  # jit.recompute_policy("stages") boundary
        std = cfg.initializer_range
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.hidden_size = cfg.hidden_size
        self.ln1 = LayerNorm(cfg.hidden_size, epsilon=1e-5)
        self.qkv = Linear(cfg.hidden_size, 3 * cfg.hidden_size,
                          weight_attr=_winit(std))
        self.proj = Linear(cfg.hidden_size, cfg.hidden_size,
                           weight_attr=_winit(std))
        self.ln2 = LayerNorm(cfg.hidden_size, epsilon=1e-5)
        self.ffn_in = Linear(cfg.hidden_size, cfg.intermediate_size,
                             weight_attr=_winit(std))
        self.ffn_out = Linear(cfg.intermediate_size, cfg.hidden_size,
                              weight_attr=_winit(std))
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.attn_dropout = cfg.attention_probs_dropout_prob
        self.act = cfg.hidden_act

    def attend(self, x, cache=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        new_cache = None
        if cache is not None:
            from ..tensor.manipulation import concat
            pk, pv = cache
            if pk.shape[1]:
                k = concat([pk, k], axis=1)
                v = concat([pv, v], axis=1)
            new_cache = (k, v)
        # causal whenever more than one query: with a kv cache the mask
        # offsets by (sk - sq), i.e. query i attends keys <= past + i
        # (both the naive tril(k=sk-sq) and the flash kernel honor this).
        ctx = F.scaled_dot_product_attention(
            q, k, v, is_causal=(s > 1),
            dropout_p=self.attn_dropout, training=self.training)
        return self.proj(ctx.reshape([b, s, self.hidden_size])), new_cache

    def forward(self, x, cache=None):
        a, new_cache = self.attend(self.ln1(x), cache)
        x = x + self.dropout(a)
        h = self.ffn_out(getattr(F, self.act)(self.ffn_in(self.ln2(x))))
        x = x + self.dropout(h)
        return x if cache is None else (x, new_cache)

    def attend_fixed(self, x, kbuf, vbuf, pos):
        """Decode attention against a FIXED-size kv buffer (B, T, H, D),
        writing this chunk's k/v at [pos, pos+s).  Static shapes keep the
        whole generate loop one compiled XLA program (no per-length retrace —
        the TPU-native replacement for the reference's growing LoD beam
        state, fluid/layers/rnn.py dynamic_decode)."""
        import jax
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        pos = unwrap(pos)
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        kbuf = jax.lax.dynamic_update_slice(
            kbuf, unwrap(k).astype(kbuf.dtype), (0, pos, 0, 0))
        vbuf = jax.lax.dynamic_update_slice(
            vbuf, unwrap(v).astype(vbuf.dtype), (0, pos, 0, 0))
        # query i (absolute pos+i) may attend buffer slots <= pos+i
        t = kbuf.shape[1]
        key_idx = jnp.arange(t)[None, :]
        q_idx = pos + jnp.arange(s)[:, None]
        mask = jnp.where(key_idx <= q_idx, 0.0, -1e30)[None, None]
        ctx = F.scaled_dot_product_attention(
            q, Tensor(kbuf.astype(unwrap(q).dtype)),
            Tensor(vbuf.astype(unwrap(q).dtype)), attn_mask=Tensor(mask),
            dropout_p=0.0, training=False)
        return self.proj(ctx.reshape([b, s, self.hidden_size])), kbuf, vbuf

    def forward_fixed(self, x, kbuf, vbuf, pos):
        a, kbuf, vbuf = self.attend_fixed(self.ln1(x), kbuf, vbuf, pos)
        x = x + a
        h = self.ffn_out(getattr(F, self.act)(self.ffn_in(self.ln2(x))))
        return x + h, kbuf, vbuf


class GPTModel(Layer):
    def __init__(self, cfg: GPTConfig = None, **kw):
        super().__init__()
        self.config = cfg or GPTConfig(**kw)
        cfg = self.config
        std = cfg.initializer_range
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         weight_attr=_winit(std))
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size,
                                             weight_attr=_winit(std))
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.blocks = LayerList([GPTBlock(cfg)
                                 for _ in range(cfg.num_hidden_layers)])
        self.ln_f = LayerNorm(cfg.hidden_size, epsilon=1e-5)

    def embed(self, input_ids, position_ids=None, past_len=0):
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        ids = unwrap(input_ids)
        if position_ids is None:
            pos = jnp.arange(past_len, past_len + ids.shape[-1],
                             dtype=jnp.int32)
            position_ids = Tensor(jnp.broadcast_to(pos, ids.shape))
        return self.dropout(self.word_embeddings(input_ids)
                            + self.position_embeddings(position_ids))

    def forward(self, input_ids, position_ids=None, cache=None):
        past_len = 0 if cache is None else cache[0][0].shape[1]
        h = self.embed(input_ids, position_ids, past_len)
        new_caches = []
        for i, blk in enumerate(self.blocks):
            if cache is not None:
                h, c = blk(h, cache[i])
                new_caches.append(c)
            else:
                h = blk(h)
        h = self.ln_f(h)
        return h if cache is None else (h, new_caches)

    def gen_cache(self, batch_size=1):
        from ..tensor.creation import zeros
        cfg = self.config
        hd = cfg.hidden_size // cfg.num_attention_heads
        return [(zeros([batch_size, 0, cfg.num_attention_heads, hd]),
                 zeros([batch_size, 0, cfg.num_attention_heads, hd]))
                for _ in range(cfg.num_hidden_layers)]

    def gen_fixed_cache(self, batch_size, max_length, dtype=None):
        """Preallocated (k, v) buffers per layer for the jitted decode loop:
        each (B, max_length, H, D) raw jax arrays.

        This (with forward_fixed below) is the serving protocol the
        continuous-batching engine consumes — paddle_tpu.serving allocates
        ONE gen_fixed_cache(max_slots, max_len) pool per engine and vmaps
        forward_fixed over the slot axis; see the serving package
        docstring for the full contract."""
        import jax.numpy as jnp
        cfg = self.config
        hd = cfg.hidden_size // cfg.num_attention_heads
        dt = dtype or jnp.float32
        shape = (batch_size, max_length, cfg.num_attention_heads, hd)
        return [(jnp.zeros(shape, dt), jnp.zeros(shape, dt))
                for _ in range(cfg.num_hidden_layers)]

    def forward_fixed(self, input_ids, caches, pos):
        """Fixed-cache forward: caches is [(kbuf, vbuf)] raw arrays, pos the
        write offset (traced scalar ok).  Returns (h, new_caches)."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        ids = unwrap(input_ids)
        s = ids.shape[-1]
        position_ids = Tensor(jnp.broadcast_to(
            unwrap(pos) + jnp.arange(s, dtype=jnp.int32), ids.shape))
        h = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids))
        new_caches = []
        for i, blk in enumerate(self.blocks):
            kbuf, vbuf = caches[i]
            h, kbuf, vbuf = blk.forward_fixed(h, kbuf, vbuf, pos)
            new_caches.append((kbuf, vbuf))
        return self.ln_f(h), new_caches


class GPTForPretraining(Layer):
    """Causal-LM pretraining head (tied embedding weights)."""

    def __init__(self, cfg: GPTConfig = None, **kw):
        super().__init__()
        self.gpt = GPTModel(cfg, **kw)

    def forward(self, input_ids, position_ids=None, cache=None,
                labels=None):
        """With `labels`, returns PER-TOKEN losses via the fused tied-head
        CE (ops/fused_ce.py) — the (B, S, V) logits never materialize
        between forward and backward, the r3-verdict big-vocab lever.
        Without labels: logits, the reference-parity contract."""
        from ..tensor.linalg import matmul
        out = self.gpt(input_ids, position_ids, cache)
        h = out[0] if isinstance(out, tuple) else out
        if labels is not None:
            if cache is not None:
                from ..core.errors import InvalidArgumentError
                raise InvalidArgumentError(
                    "[gpt] labels with cache is unsupported — the fused CE "
                    "is a training path; compute losses from the returned "
                    "logits when decoding")
            from ..ops.fused_ce import fused_linear_cross_entropy
            return fused_linear_cross_entropy(
                h, self.gpt.word_embeddings.weight, labels)
        logits = matmul(h, self.gpt.word_embeddings.weight, transpose_y=True)
        return logits if cache is None else (logits, out[1])

    # --- generation protocol (paddle_tpu.generation.generate) ---
    def gen_fixed_cache(self, batch_size, max_length, dtype=None):
        return self.gpt.gen_fixed_cache(batch_size, max_length, dtype)

    def forward_fixed(self, input_ids, caches, pos):
        from ..tensor.linalg import matmul
        h, caches = self.gpt.forward_fixed(input_ids, caches, pos)
        logits = matmul(h, self.gpt.word_embeddings.weight, transpose_y=True)
        return logits, caches

    def generate(self, input_ids, **kwargs):
        """Greedy / sampling / beam-search decoding over the jitted
        fixed-cache decode loop — see paddle_tpu.generation.generate."""
        from ..generation import generate
        return generate(self, input_ids, **kwargs)


class GPTPretrainingCriterion(Layer):
    def forward(self, logits, labels, loss_mask=None):
        loss = F.cross_entropy(logits.reshape([-1, logits.shape[-1]]),
                               labels.reshape([-1]), reduction="none")
        if loss_mask is not None:
            m = loss_mask.reshape([-1])
            from ..tensor.math import sum as tsum  # noqa: A004
            return tsum(loss * m) / tsum(m)
        from ..tensor.stat import mean
        return mean(loss)
