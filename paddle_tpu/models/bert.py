"""BERT model family (flagship encoder model; benchmark config #3/#4).

The reference repo carries BERT/ERNIE-style transformers through its test
models (dist_transformer.py) and through `paddle.nn.TransformerEncoder`
(python/paddle/nn/layer/transformer.py); the pretraining configs targeted by
BASELINE.md (BERT-base/large, ERNIE-large) are built here natively.

TPU-first notes: attention routes through F.scaled_dot_product_attention →
pallas flash kernel; all matmuls are (B*S, H)×(H, ...) shapes that tile onto
the MXU; the whole model jits into a single XLA program (no per-op dispatch).
"""
from __future__ import annotations

import math

from ..nn.layer_base import Layer
from ..nn.layer.common import Linear, Dropout, Embedding
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer_base import ParamAttr


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072,
                 hidden_act="gelu", hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1, max_position_embeddings=512,
                 type_vocab_size=2, initializer_range=0.02, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id


def bert_base_config(**kw):
    return BertConfig(**kw)


def bert_large_config(**kw):
    base = dict(hidden_size=1024, num_hidden_layers=24,
                num_attention_heads=16, intermediate_size=4096)
    base.update(kw)
    return BertConfig(**base)


def _winit(std):
    return ParamAttr(initializer=I.Normal(0.0, std))


class BertEmbeddings(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        std = cfg.initializer_range
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         weight_attr=_winit(std))
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size,
                                             weight_attr=_winit(std))
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size,
                                               weight_attr=_winit(std))
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def _sum_embeddings(self, input_ids, token_type_ids=None,
                        position_ids=None):
        """word+position+token_type sum before norm/dropout (subclass hook)."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        ids = unwrap(input_ids)
        seq = ids.shape[-1]
        if position_ids is None:
            position_ids = Tensor(
                jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32), ids.shape))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros_like(ids))
        return (self.word_embeddings(input_ids)
                + self.position_embeddings(position_ids)
                + self.token_type_embeddings(token_type_ids))

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        emb = self._sum_embeddings(input_ids, token_type_ids, position_ids)
        return self.dropout(self.layer_norm(emb))


class BertSelfAttention(Layer):
    """Fused-QKV attention block (the reference's fused/multihead_matmul
    equivalent): one (H, 3H) matmul then flash attention."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        std = cfg.initializer_range
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.hidden_size = cfg.hidden_size
        self.qkv = Linear(cfg.hidden_size, 3 * cfg.hidden_size,
                          weight_attr=_winit(std))
        self.out = Linear(cfg.hidden_size, cfg.hidden_size,
                          weight_attr=_winit(std))
        self.attn_dropout = cfg.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        ctx = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout,
            training=self.training)
        return self.out(ctx.reshape([b, s, self.hidden_size]))


class BertLayer(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        std = cfg.initializer_range
        self.attention = BertSelfAttention(cfg)
        self.attn_norm = LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.ffn_in = Linear(cfg.hidden_size, cfg.intermediate_size,
                             weight_attr=_winit(std))
        self.ffn_out = Linear(cfg.intermediate_size, cfg.hidden_size,
                              weight_attr=_winit(std))
        self.ffn_norm = LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.dropout = Dropout(cfg.hidden_dropout_prob)
        self.act = cfg.hidden_act

    def forward(self, x, attn_mask=None):
        x = self.attn_norm(x + self.dropout(self.attention(x, attn_mask)))
        act = getattr(F, self.act)
        h = self.ffn_out(act(self.ffn_in(x)))
        return self.ffn_norm(x + self.dropout(h))


class BertPooler(Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size,
                            weight_attr=_winit(cfg.initializer_range))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, cfg: BertConfig = None, **kw):
        super().__init__()
        self.config = cfg or BertConfig(**kw)
        cfg = self.config
        self.embeddings = self._make_embeddings(cfg)
        self.layers = LayerList([BertLayer(cfg)
                                 for _ in range(cfg.num_hidden_layers)])
        self.pooler = BertPooler(cfg)

    def _make_embeddings(self, cfg):
        return BertEmbeddings(cfg)

    def make_attn_mask(self, input_ids, attention_mask=None):
        """(B,S) padding mask / None -> additive (B,1,1,S) float mask / None."""
        import jax.numpy as jnp
        from ..core.tensor import Tensor, unwrap
        if attention_mask is None:
            return None
        m = unwrap(attention_mask)
        if m.ndim == 2:
            m = m[:, None, None, :]
        return Tensor((1.0 - m.astype(jnp.float32)) * -1e4)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        mask = self.make_attn_mask(input_ids, attention_mask)
        h = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.layers:
            h = layer(h, mask)
        return h, self.pooler(h)


class BertLMHead(Layer):
    """MLM head with tied decoder weight (transform + layernorm + logits)."""

    def __init__(self, cfg: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=_winit(cfg.initializer_range))
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=1e-12)
        self.act = cfg.hidden_act
        self.decoder_weight = embedding_weights  # (V, H), tied
        self.decoder_bias = self.create_parameter(
            [cfg.vocab_size], is_bias=True)

    def forward(self, hidden):
        from ..tensor.linalg import matmul
        h = self.layer_norm(getattr(F, self.act)(self.transform(hidden)))
        return matmul(h, self.decoder_weight, transpose_y=True) \
            + self.decoder_bias


class BertForPretraining(Layer):
    """MLM + NSP pretraining model (benchmark flagship)."""

    def __init__(self, cfg: BertConfig = None, **kw):
        super().__init__()
        self.bert = BertModel(cfg, **kw)
        cfg = self.bert.config
        self.cls = BertLMHead(cfg, self.bert.embeddings.word_embeddings.weight)
        self.nsp = Linear(cfg.hidden_size, 2,
                          weight_attr=_winit(cfg.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq_out, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                    attention_mask)
        return self.cls(seq_out), self.nsp(pooled)


class BertPretrainingCriterion(Layer):
    """Masked-LM + next-sentence loss (ignore_index=-100 for unmasked)."""

    def __init__(self, vocab_size=None):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score,
                masked_lm_labels, next_sentence_labels=None):
        mlm = F.cross_entropy(
            prediction_scores.reshape([-1, prediction_scores.shape[-1]]),
            masked_lm_labels.reshape([-1]), ignore_index=-100,
            reduction="mean")
        if next_sentence_labels is None:
            return mlm
        nsp = F.cross_entropy(seq_relationship_score,
                              next_sentence_labels.reshape([-1]),
                              reduction="mean")
        return mlm + nsp


class BertForSequenceClassification(Layer):
    def __init__(self, cfg: BertConfig = None, num_classes=2, dropout=None, **kw):
        super().__init__()
        self.bert = BertModel(cfg, **kw)
        cfg = self.bert.config
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 weight_attr=_winit(cfg.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))
