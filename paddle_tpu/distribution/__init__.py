"""Probability distributions (reference: python/paddle/distribution.py —
Distribution/Uniform/Normal/Categorical)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng as _rng
from ..core.tensor import Tensor, unwrap


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(unwrap(self.log_prob(value))))

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = jnp.asarray(unwrap(low), jnp.float32)
        self.high = jnp.asarray(unwrap(high), jnp.float32)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape, self.high.shape)
        key = jax.random.key(seed) if seed else _rng.next_key()
        u = jax.random.uniform(key, shape)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = unwrap(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = jnp.asarray(unwrap(loc), jnp.float32)
        self.scale = jnp.asarray(unwrap(scale), jnp.float32)

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape, self.scale.shape)
        key = jax.random.key(seed) if seed else _rng.next_key()
        z = jax.random.normal(key, shape)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = unwrap(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = jnp.asarray(unwrap(logits), jnp.float32)

    def _probs(self):
        return jax.nn.softmax(self.logits, axis=-1)

    def sample(self, shape=(), seed=0):
        key = jax.random.key(seed) if seed else _rng.next_key()
        return Tensor(jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.logits.shape[:-1]))

    def log_prob(self, value):
        v = unwrap(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def entropy(self):
        p = self._probs()
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(-jnp.sum(p * logp, axis=-1))

    def kl_divergence(self, other):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        logq = jax.nn.log_softmax(other.logits, axis=-1)
        p = self._probs()
        return Tensor(jnp.sum(p * (logp - logq), axis=-1))


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference:
    python/paddle/distribution.py MultivariateNormalDiag)."""

    def __init__(self, loc, scale):
        self.loc = unwrap(loc).astype(jnp.float32)
        self.scale = unwrap(scale).astype(jnp.float32)
        # a diagonal MATRIX has exactly one more axis than loc; anything
        # else is a (batch of) scale vectors — shape equality alone would
        # misread a (D, D) batch of vectors as one matrix
        if self.scale.ndim == self.loc.ndim + 1 and \
                self.scale.shape[-1] == self.scale.shape[-2]:
            self._diag = jnp.diagonal(self.scale, axis1=-2, axis2=-1)
        else:
            self._diag = self.scale

    def sample(self, shape=(), seed=0):
        key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
        eps = jax.random.normal(
            key, tuple(shape) + self.loc.shape, jnp.float32)
        return Tensor(self.loc + eps * self._diag)

    def log_prob(self, value):
        v = unwrap(value).astype(jnp.float32)
        var = self._diag ** 2
        d = self.loc.shape[-1]
        lp = -0.5 * jnp.sum((v - self.loc) ** 2 / var, -1) \
            - 0.5 * d * jnp.log(2 * jnp.pi) - jnp.sum(jnp.log(self._diag), -1)
        return Tensor(lp)

    def entropy(self):
        d = self.loc.shape[-1]
        return Tensor(0.5 * d * (1 + jnp.log(2 * jnp.pi))
                      + jnp.sum(jnp.log(self._diag), -1))

    def kl_divergence(self, other):
        v1, v2 = self._diag ** 2, other._diag ** 2
        kl = 0.5 * jnp.sum(v1 / v2 + (other.loc - self.loc) ** 2 / v2
                           - 1.0 + jnp.log(v2) - jnp.log(v1), -1)
        return Tensor(kl)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64"):  # noqa: A002
    """Sample one category index per row of a probability matrix
    (reference: operators/sampling_id_op)."""
    from ..core.dtype import convert_dtype
    probs = unwrap(x).astype(jnp.float32)
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    idx = jax.random.categorical(key, jnp.log(jnp.maximum(probs, 1e-30)))
    return Tensor(idx.astype(convert_dtype(dtype)))
