"""Exportable TRAINING programs: save a fused train step as a serialized
XLA artifact a host process can drive without the Python model code.

Reference: paddle/fluid/train/demo/demo_trainer.cc:1 — Python saves a
ProgramDesc (train_program + startup), a standalone C++ binary loads it and
drives the executor per batch.  TPU-native: the whole fused
forward+backward+optimizer step (the TrainStep program) exports through
jax.export as StableHLO with its state pytree spec; `TrainSession` replays
it batch-by-batch, and the C ABI (native/src/capi.cc PD_CreateTrainer /
PD_TrainerStep) exposes the session to C/Go hosts (demo/train_demo.c).
"""
from __future__ import annotations

import os
import pickle
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["save_train_program", "TrainSession"]


def save_train_program(model, loss_fn, optimizer, path: str,
                       input_specs: Sequence, amp_level=None,
                       amp_dtype="bfloat16", remat=False, seed: int = 0):
    """Serialize one optimizer step (fwd+bwd+update, concrete shapes) plus
    the initial train state.

    input_specs: list of InputSpec/(shape, dtype) for the step's batch
    (inputs..., label).  Writes path.pdtrain (StableHLO), path.pdstate.npz
    (params + opt state leaves), path.pdtrainmeta (pytree specs).

    The exported program IS TrainStep's compiled step (same builder —
    sparse-grad probe, remat, AMP and all), so the artifact can never
    diverge from what the in-process step computes.
    """
    from . import InputSpec, TrainStep, state_arrays

    tstep = TrainStep(model, loss_fn, optimizer, amp_level=amp_level,
                      amp_dtype=amp_dtype, remat=remat)
    state = state_arrays(model)
    opt_state = tstep.init_opt_state(state)

    def to_sds(s):
        if isinstance(s, InputSpec):
            return s.to_shape_dtype()
        shape, dtype = s
        return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))

    batch_sds = tuple(to_sds(s) for s in input_specs)
    # the sparse-probe inside _build traces the forward, so hand it real
    # (zero) example arrays rather than abstract shapes
    example_batch = tuple(jnp.zeros(s.shape, s.dtype) for s in batch_sds)
    compiled = tstep._build(state, opt_state, example_batch)

    state_sds = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), state)
    opt_sds = jax.tree_util.tree_map(
        lambda v: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype),
        opt_state)

    from jax import export as jax_export
    exported = jax_export.export(compiled)(
        state_sds, opt_sds,
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((2,), jnp.uint32),
        batch_sds)

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdtrain", "wb") as f:
        f.write(exported.serialize())
    sleaves, streedef = jax.tree_util.tree_flatten(state)
    oleaves, otreedef = jax.tree_util.tree_flatten(opt_state)
    np.savez(path + ".pdstate.npz",
             **{f"s{i}": np.asarray(v) for i, v in enumerate(sleaves)},
             **{f"o{i}": np.asarray(v) for i, v in enumerate(oleaves)})
    with open(path + ".pdtrainmeta", "wb") as f:
        pickle.dump({
            "state_treedef": streedef, "opt_treedef": otreedef,
            "n_state": len(sleaves), "n_opt": len(oleaves),
            "lr": float(optimizer.get_lr()), "seed": int(seed),
            "batch_specs": [(tuple(s.shape), str(np.dtype(s.dtype)))
                            for s in batch_sds],
        }, f)
    return path


class TrainSession:
    """Drive a saved train program: holds the state, steps per batch.
    The host-language twin lives behind PD_CreateTrainer in the C ABI."""

    def __init__(self, path: str):
        from jax import export as jax_export
        with open(path + ".pdtrain", "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        with open(path + ".pdtrainmeta", "rb") as f:
            meta = pickle.load(f)
        data = np.load(path + ".pdstate.npz")
        sleaves = [jnp.asarray(data[f"s{i}"])
                   for i in range(meta["n_state"])]
        oleaves = [jnp.asarray(data[f"o{i}"]) for i in range(meta["n_opt"])]
        self._state = jax.tree_util.tree_unflatten(meta["state_treedef"],
                                                   sleaves)
        self._opt_state = jax.tree_util.tree_unflatten(meta["opt_treedef"],
                                                       oleaves)
        self._meta = meta
        self._step_no = 0
        self._key = jax.random.PRNGKey(meta["seed"])
        self.lr = meta["lr"]

    @property
    def batch_specs(self):
        return list(self._meta["batch_specs"])

    def step(self, *batch) -> float:
        """One optimizer step on numpy/jax batch arrays; returns the loss."""
        self._step_no += 1
        key = jax.random.fold_in(self._key, self._step_no)
        args = tuple(jnp.asarray(b) for b in batch)
        self._state, self._opt_state, loss, _outs = self._exported.call(
            self._state, self._opt_state,
            jnp.int32(self._step_no), jnp.float32(self.lr),
            jax.random.key_data(key), args)
        return float(loss)

    def state_dict(self):
        return {k: np.asarray(v) for k, v in self._state.items()}
