"""paddle_tpu.jit — the "static graph world" replacement.

Reference: @paddle.jit.to_static / ProgramTranslator
(python/paddle/fluid/dygraph/dygraph_to_static/program_translator.py:729) turn
dygraph Python into a ProgramDesc via AST rewriting; save_inference_model
serializes program + params; AnalysisPredictor serves it.

TPU-native: tracing *is* the program capture — `functional_call` runs a Layer's
forward with parameters injected as jax values and the tape off, so `jax.jit`
(+AOT `jax.export`) replaces ProgramDesc/Executor/AnalysisPredictor, buffer
donation replaces inplace/memory-optimize passes, and `TrainStep` fuses
forward+backward+optimizer into one compiled XLA program (what the reference
needs a whole SSA-graph ParallelExecutor for).
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import buffer_updates as _bufup
from ..core import recompute as _recompute
from ..core.layout import layout_policy  # noqa: F401  (public: jit.layout_policy)
from ..core.recompute import recompute_policy  # noqa: F401  (public: jit.recompute_policy)
from ..core.tensor import Tensor, no_grad, unwrap
from ..nn.layer_base import Layer


# ---------------------------------------------------------------------------
# functional_call: run a Layer with params supplied as values
# ---------------------------------------------------------------------------

def state_arrays(layer: Layer) -> Dict[str, Any]:
    """Named param+buffer raw arrays (the layer's pytree leaves)."""
    return {k: v._data for k, v in layer.state_dict().items()}


def functional_call(layer: Layer, state: Dict[str, Any], *args,
                    training: Optional[bool] = None, method: str = None,
                    buffer_updates: Optional[Dict[str, Any]] = None,
                    **kwargs):
    """Run layer.forward with `state` (name -> raw array) swapped in.

    Works under jit tracing: swapping happens at trace time only.  Tape is
    disabled so the pure-functional jax.grad path is used for autodiff.
    `method` selects an alternative entry point (e.g. a fixed-cache decode
    forward) instead of __call__.  When `buffer_updates` (a dict) is
    passed, in-place buffer writes made during the forward (BatchNorm
    running stats) are captured FUNCTIONALLY instead of applied: the dict
    is filled with {state_key: new_raw_value} so a compiled train step can
    fold them into its next-state outputs (no host round-trip under jit).
    """
    sd = layer.state_dict()
    originals = {k: t._data for k, t in sd.items()}
    modes = None
    if training is not None:
        modes = [(l, l.training) for l in layer.sublayers(include_self=True)]
        for l, _ in modes:
            l.training = training
    try:
        for k, t in sd.items():
            if k in state:
                t._data = state[k]
        entry = getattr(layer, method) if method else layer
        with no_grad():
            if buffer_updates is not None:
                with _bufup.capture() as log:
                    out = entry(*_wrap_args(args), **kwargs)
                buffer_updates.update(_bufup.resolve(log, sd))
            else:
                out = entry(*_wrap_args(args), **kwargs)
        return _extract_raw(out)
    finally:
        for k, t in sd.items():
            t._data = originals[k]
        if modes is not None:
            for l, m in modes:
                l.training = m


def _wrap_args(args):
    return tuple(Tensor(a) if isinstance(a, (jax.Array, np.ndarray)) or _is_tracer(a)
                 else a for a in args)


def _extract_raw(out):
    """Tensor pytree -> raw arrays; a layout boundary: rank-4 tensors the
    layout policy left physically NHWC are transposed back to the logical
    NCHW the caller expects (loss functions, hapi metrics, predict)."""
    def leaf(x):
        if not isinstance(x, Tensor):
            return x
        if x._layout is not None and x._data.ndim == 4:
            return jnp.transpose(x._data, (0, 3, 1, 2))
        return x._data
    return jax.tree_util.tree_map(leaf, out,
                                  is_leaf=lambda x: isinstance(x, Tensor))


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


# ---------------------------------------------------------------------------
# to_static
# ---------------------------------------------------------------------------

class InputSpec:
    """paddle.static.InputSpec equivalent."""

    def __init__(self, shape, dtype="float32", name=None):
        from ..core.dtype import convert_dtype
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    def to_shape_dtype(self, batch=1):
        shape = tuple(batch if s == -1 else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class StaticFunction:
    """Result of @to_static: compiled execution of a Layer/function."""

    def __init__(self, fn: Callable, layer: Optional[Layer] = None,
                 input_spec=None):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        self._compiled = None

    @property
    def _pure(self):
        if self._compiled is None:
            if self._layer is not None:
                layer, fn = self._layer, self._fn

                def pure(state, *args, **kwargs):
                    sd = layer.state_dict()
                    originals = {k: t._data for k, t in sd.items()}
                    try:
                        for k, t in sd.items():
                            if k in state:
                                t._data = state[k]
                        with no_grad():
                            out = fn(*_wrap_args(args), **kwargs)
                        return _extract_raw(out)
                    finally:
                        for k, t in sd.items():
                            t._data = originals[k]
            else:
                fn = self._fn

                def pure(state, *args, **kwargs):
                    with no_grad():
                        out = fn(*_wrap_args(args), **kwargs)
                    return _extract_raw(out)
            from ..observability import track
            label = (type(self._layer).__name__ if self._layer is not None
                     else getattr(self._fn, "__name__", "fn"))
            self._compiled = track(f"to_static:{label}", jax.jit(pure))
        return self._compiled

    def __call__(self, *args, **kwargs):
        state = state_arrays(self._layer) if self._layer is not None else {}
        raw_args = tuple(unwrap(a) for a in args)
        out = self._pure(state, *raw_args, **kwargs)
        return jax.tree_util.tree_map(lambda x: Tensor(x), out)

    def concrete_program(self, *args):
        return self

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, **kwargs):
    """@paddle.jit.to_static — here: jit the forward (tape off, donation-ready)."""
    def decorate(fn):
        if isinstance(fn, Layer):
            return StaticFunction(fn.forward, layer=fn, input_spec=input_spec)
        # bound method of a Layer?
        self_obj = getattr(fn, "__self__", None)
        if isinstance(self_obj, Layer):
            return StaticFunction(fn, layer=self_obj, input_spec=input_spec)
        return StaticFunction(fn, input_spec=input_spec)
    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    return fn


# ---------------------------------------------------------------------------
# TrainStep: fused forward+backward+optimizer, fully jitted with donation
# ---------------------------------------------------------------------------

def forward_loss(model, loss_fn, state, batch, rng_key=None, amp_level=None,
                 amp_dtype="bfloat16", return_outputs=False,
                 return_buffer_updates=False):
    """Shared traced forward+loss used by TrainStep / ShardedTrainStep:
    functional_call with a per-step rng root (fresh dropout masks each step)
    and optional bf16 autocast.  With return_outputs, also returns the raw
    forward outputs (so hapi metrics reuse the training forward instead of
    paying a second one).  With return_buffer_updates, in-place buffer
    writes (BatchNorm running stats) are captured functionally and
    returned as a third element {state_key: new_raw} — the compiled step
    folds them into its next state instead of freezing them under jit."""
    import contextlib
    from .. import amp as amp_mod
    from ..core import rng as _rng

    def run():
        bufs = {} if return_buffer_updates else None
        out = functional_call(model, state, *batch[:-1], training=True,
                              buffer_updates=bufs)
        label = Tensor(batch[-1])
        outs = out if isinstance(out, tuple) else (out,)
        loss = loss_fn(*[Tensor(o) for o in outs], label)
        if return_buffer_updates:
            return unwrap(loss), outs if return_outputs else (), bufs
        if return_outputs:
            return unwrap(loss), outs
        return unwrap(loss)

    keyctx = (_rng.key_ctx(rng_key) if rng_key is not None
              else contextlib.nullcontext())
    with keyctx:
        if amp_level:
            with amp_mod.auto_cast(level=amp_level, dtype=amp_dtype):
                return run()
        return run()

_obs_step_hist = None


def _step_hist():
    """train_step_seconds histogram handle (created once; registry.reset()
    zeroes values in place so the cache stays valid)."""
    global _obs_step_hist
    if _obs_step_hist is None:
        from ..observability import metrics as _m
        _obs_step_hist = _m.histogram(
            "train_step_seconds",
            "host wall time per TrainStep/ShardedTrainStep call (dispatch "
            "+ any synchronous device wait)")
    return _obs_step_hist


def warm_step_program(compiled_fn, state, opt_state, optimizer, raw_batch):
    """Compile a train-step program for this signature WITHOUT executing
    it — the shared half of `TrainStep.warmup` / `ShardedTrainStep.warmup`
    (one place for the calling convention so the two step classes cannot
    drift).  Stand-ins for the per-call dynamic scalars are
    aval-identical to a real call's; the key is a CONSTANT (not
    `_rng.next_key()`: warming must not consume the stream a bit-exact
    resume depends on).  Returns whether a compile happened."""
    from ..core import rng as _rng
    args = (state, opt_state,
            jnp.asarray(optimizer._step_count + 1, jnp.int32),
            jnp.asarray(optimizer.get_lr(), jnp.float32),
            _rng.example_key(), raw_batch)
    if hasattr(compiled_fn, "warm"):              # TrackedJit
        return bool(compiled_fn.warm(*args))
    # PDTPU_OBS_PROGRAMS=0: compile without executing; the first call
    # retraces but hits the persistent cache
    try:
        compiled_fn.lower(*args).compile()
        return True
    except Exception:
        return False


def guard_select(params, opt_state, new_params, new_opt, loss, grads):
    """Device-side step guard, shared by TrainStep / ShardedTrainStep.

    Computes loss + global-grad-norm finiteness INSIDE the compiled step
    (no extra host sync: the scalars ride out as two more outputs the host
    reads together with the loss it was reading anyway) and selects the
    pre-update state when the step is bad — a NaN/Inf batch leaves params,
    optimizer moments, AND BatchNorm running stats untouched.  This is the
    skip half of GradScaler's skip-and-decay, applied even without AMP.

    Returns (guarded_params, guarded_opt, grad_norm, ok).
    """
    from ..core.selected_rows import RowSparseGrad
    leaves = [g.values if isinstance(g, RowSparseGrad) else g
              for g in grads.values()]
    if leaves:
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                             for l in leaves))
    else:
        gnorm = jnp.float32(0)
    ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)

    def sel(new, old):
        return jnp.where(ok, new, old)

    return (jax.tree_util.tree_map(sel, new_params, params),
            jax.tree_util.tree_map(sel, new_opt, opt_state),
            gnorm, ok)


class TrainStep:
    """One compiled training step (the perf path used by hapi/bench).

    step(params, opt_state, step_no, lr, *batch) -> (params', opt_state', loss)
    with `params`/`opt_state` donated — the XLA analogue of the reference's
    fused-allreduce + inplace-addto passes is simply donation + XLA fusion.

    guard=True compiles the finiteness guard into the step (see
    guard_select) and exposes per-step (grad_norm, ok) on `last_guard`;
    utils.guarded.GuardedTrainStep adds the host-side policy (spike window,
    quarantine records, rollback).
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 amp_level: Optional[str] = None, amp_dtype="bfloat16",
                 mesh=None, batch_sharding=None, remat: bool = False,
                 with_outputs: bool = False, guard: bool = False,
                 accum_steps: int = 1):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        # gradient accumulation: the step takes the FULL logical batch,
        # splits it into accum_steps micro-batches inside ONE compiled
        # program (lax.scan, f32 grad accumulators) and applies ONE
        # optimizer update — b>256-equivalent towers train in the
        # micro-batch activation envelope with the compile count unchanged
        self.accum_steps = int(accum_steps)
        if self.accum_steps < 1:
            raise ValueError("TrainStep: accum_steps must be >= 1")
        if self.accum_steps > 1 and with_outputs:
            raise ValueError(
                "TrainStep: with_outputs does not compose with "
                "accum_steps>1 (per-micro-batch forward outputs would "
                "have to be stacked across the scan; run the forward "
                "separately if metrics need it)")
        # with_outputs: the compiled step also returns the forward outputs
        # (hapi metric reuse) — on the sparse-grad path too (step_sparse
        # threads them through the aux channel)
        self._with_outputs = with_outputs
        self.last_outputs = None
        self._names = list(model.state_dict().keys())
        self._trainable = {k for k, v in model.state_dict().items()
                           if getattr(v, "trainable", False)}
        # params flagged by Embedding(sparse=True): their grads flow as
        # RowSparseGrad through the zeros-cotangent channel (selected_rows.py)
        self._sparse = {k for k, v in model.state_dict().items()
                        if getattr(v, "sparse_grad", False)}
        # row-sharded tables (embedding.ShardedEmbedding): their sparse
        # grads take the per-shard lazy update inside the compiled step
        self._row_shard = {
            k: (v.row_shard_axis, v.row_shard_mesh)
            for k, v in model.state_dict().items()
            if getattr(v, "row_shard_axis", None) is not None
            and getattr(v, "row_shard_mesh", None) is not None}
        if self.accum_steps > 1 and self._sparse:
            raise NotImplementedError(
                f"TrainStep(accum_steps={self.accum_steps}) does not "
                f"compose with sparse-grad embedding weights "
                f"{sorted(self._sparse)}: per-micro-batch RowSparseGrads "
                "would need a row-union merge inside the accumulation "
                "scan.  Rebuild the offending Embedding/ShardedEmbedding "
                "layers with sparse=False (dense grads accumulate fine) "
                "or run accum_steps=1")
        self._sig_cache = {}
        self._sparse_checked = False
        # param names demoted to DENSE grads (tied weights): sparse grads
        # would drop the other uses' gradients, so fall back instead of
        # erroring (the reference's lazy-mode Adam likewise densifies when
        # the lookup table is shared)
        self._sparse_deny = set()
        if self._sparse:
            by_obj = {}
            for k, v in model.state_dict().items():
                by_obj.setdefault(id(v), (v, []))[1].append(k)
            for v, names in by_obj.values():
                if len(names) > 1 and self._sparse.intersection(names):
                    import warnings
                    warnings.warn(
                        f"Embedding(sparse=True) weight registered under "
                        f"multiple names {names} (tied weight) — falling "
                        "back to a dense gradient for it so the other "
                        "uses' gradients are kept", UserWarning)
                    self._sparse_deny.add(
                        getattr(v, "name", None) or names[0])
        self._compiled = None
        self._compiled_multi = None
        self._opt_state = None
        self._remat = remat
        self._guard = bool(guard)
        # (grad_norm, ok) device scalars from the last guarded call; read
        # them together with the loss to avoid an extra host sync
        self.last_guard = None

    def _forward_loss(self, state, batch, rng_key=None):
        return forward_loss(self.model, self.loss_fn, state, batch, rng_key,
                            self.amp_level, self.amp_dtype)

    def _sparse_setup(self, example_state, example_batch):
        """Shared sparse-grad preamble for the single- and multi-step
        builds: shape-probe each sparse lookup's (n, width, dtype), map ctx
        keys back to state keys, and run the dense-consumption guard once
        (its verdict is shape-independent).  A sparse weight the traced
        forward ALSO consumes densely (tied LM head) is demoted to dense
        grads with a one-time warning — erroring would reject the
        era-typical tied-embedding config."""
        from ..core import selected_rows as sr
        # ctx keys carry the param's unique .name; map back to state keys
        name_to_key = {getattr(v, "name", None) or k: k
                       for k, v in self.model.state_dict().items()}
        while True:
            rec = sr.SparseGradContext("record", deny=self._sparse_deny)
            with sr.use_ctx(rec):
                jax.eval_shape(
                    lambda s, b: self._forward_loss(
                        s, b, jax.random.PRNGKey(0)),
                    example_state, example_batch)
            sparse_specs = rec.specs
            sparse_names = {name_to_key[sr.param_name(k)]
                            for k in sparse_specs}
            if self._sparse_checked or not sparse_specs:
                break

            def probe(sparse_vals):
                zs = {k: jnp.zeros((n, w), dt)
                      for k, (n, w, dt) in sparse_specs.items()}
                full = dict(example_state)
                full.update(sparse_vals)
                ctx = sr.SparseGradContext("apply", zeros=zs,
                                           deny=self._sparse_deny)
                with sr.use_ctx(ctx):
                    return self._forward_loss(full, example_batch,
                                              jax.random.PRNGKey(0))
            bad = sr.dense_consumed_keys(
                probe, {k: example_state[k] for k in sparse_names})
            if not bad:
                break
            import warnings
            warnings.warn(
                f"Embedding(sparse=True) weights {sorted(bad)} are also "
                "consumed densely (tied head) — falling back to dense "
                "gradients for them so those uses' gradients are kept",
                UserWarning)
            key_to_name = {v: k for k, v in name_to_key.items()}
            self._sparse_deny.update(key_to_name[k] for k in bad)
        self._sparse_checked = True
        return sparse_specs, name_to_key, sparse_names

    @staticmethod
    def _merge_sparse_grads(grads, zgrads, ids, params, name_to_key):
        """Fold the zeros-cotangent channel into the dense grad dict as
        RowSparseGrads (shared by the single- and multi-step sparse
        builds)."""
        from ..core import selected_rows as sr
        grads = dict(grads)
        for zk, zg in zgrads.items():
            nm = name_to_key[sr.param_name(zk)]
            rsg = sr.RowSparseGrad(ids[zk], zg, params[nm].shape)
            grads[nm] = (grads[nm] + rsg) if nm in grads else rsg
        return grads

    def _build(self, example_state, example_opt, example_batch):
        from ..optimizer.functional import apply_updates, decay_flags
        opt = self.optimizer
        trainable = self._trainable
        # structured param names let AdamW's apply_decay_param_fun work here
        decay = decay_flags(opt, trainable)

        sparse_specs, sparse_names, name_to_key = {}, set(), {}
        if self._sparse:
            sparse_specs, name_to_key, sparse_names = self._sparse_setup(
                example_state, example_batch)

        with_outputs = self._with_outputs
        guard = self._guard
        accum = self.accum_steps
        from ..utils import faults as _faults

        def accum_grads(params, step_no, lr, rng_key, batch):
            """K micro-batches through an in-program lax.scan: f32 grad
            accumulators, per-micro rng keys (fold_in), BatchNorm
            running-stat updates compounding sequentially through the
            carry.  Returns (mean loss, averaged grads, params with the
            final buffer state).  Only one micro-batch's activations are
            live at a time — the whole point."""
            for b in batch:
                if b.shape[0] % accum:
                    raise ValueError(
                        f"TrainStep(accum_steps={accum}): batch dim "
                        f"{b.shape[0]} is not divisible by accum_steps")
            split = tuple(
                b.reshape((accum, b.shape[0] // accum) + b.shape[1:])
                for b in batch)
            zero = {k: jnp.zeros(params[k].shape, jnp.float32)
                    for k in trainable}

            def micro(carry, xs):
                cur, acc = carry
                mb, i = xs
                key = jax.random.fold_in(rng_key, i)

                def loss_of(train_params):
                    full = dict(cur)
                    full.update(train_params)
                    loss, _outs, bufs = forward_loss(
                        self.model, self.loss_fn, full, mb, key,
                        self.amp_level, self.amp_dtype,
                        return_buffer_updates=True)
                    return loss, bufs

                lfn = _recompute.checkpoint(loss_of) if self._remat else loss_of
                (loss, bufs), g = jax.value_and_grad(lfn, has_aux=True)(
                    {k: cur[k] for k in trainable})
                acc = {k: acc[k] + g[k].astype(jnp.float32) for k in acc}
                nxt = dict(cur)
                nxt.update(bufs)
                return (nxt, acc), loss

            (cur, acc), losses = jax.lax.scan(
                micro, (dict(params), zero), (split, jnp.arange(accum)))
            grads = {k: (acc[k] / accum).astype(params[k].dtype)
                     for k in acc}
            return jnp.mean(losses), grads, cur

        def step(params, opt_state, step_no, lr, rng_key, batch):
            if accum > 1:
                loss, grads, carried = accum_grads(
                    params, step_no, lr, rng_key, batch)
                outs, bufs = (), {k: v for k, v in carried.items()
                                  if k not in trainable}
            else:
                def loss_of(train_params):
                    full = dict(params)
                    full.update(train_params)
                    loss, outs, bufs = forward_loss(
                        self.model, self.loss_fn, full, batch, rng_key,
                        self.amp_level, self.amp_dtype,
                        return_outputs=with_outputs,
                        return_buffer_updates=True)
                    return loss, (outs, bufs)

                train_params = {k: v for k, v in params.items()
                                if k in trainable}
                loss_fn = _recompute.checkpoint(loss_of) if self._remat else loss_of
                (loss, (outs, bufs)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(train_params)
            # trace-time gated: identity (zero compiled ops) unless armed
            grads = _faults.poison_grads(grads, step_no)
            new_params, new_opt = apply_updates(
                opt, params, grads, opt_state, lr, step_no, decay)
            # running-stat (buffer) updates captured in the traced forward
            # ride the same compiled step — no eager _set_data round-trip
            new_params.update(bufs)
            if guard:
                new_params, new_opt, gnorm, ok = guard_select(
                    params, opt_state, new_params, new_opt, loss, grads)
                return new_params, new_opt, loss, outs, gnorm, ok
            return new_params, new_opt, loss, outs

        def step_sparse(params, opt_state, step_no, lr, rng_key, batch):
            from ..core import selected_rows as sr
            zeros = {k: jnp.zeros((n, w), dt)
                     for k, (n, w, dt) in sparse_specs.items()}

            def loss_of(train_params, zvals):
                full = dict(params)
                full.update(train_params)
                ctx = sr.SparseGradContext("apply", zeros=zvals,
                                           deny=self._sparse_deny)
                with sr.use_ctx(ctx):
                    loss, outs, bufs = forward_loss(
                        self.model, self.loss_fn, full, batch, rng_key,
                        self.amp_level, self.amp_dtype,
                        return_outputs=with_outputs,
                        return_buffer_updates=True)
                return loss, (ctx.ids, outs, bufs)

            train_params = {k: v for k, v in params.items()
                            if k in trainable and k not in sparse_names}
            loss_fn = _recompute.checkpoint(loss_of) if self._remat else loss_of
            (loss, (ids, outs, bufs)), (grads, zgrads) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True)(train_params, zeros)
            grads = self._merge_sparse_grads(grads, zgrads, ids, params,
                                             name_to_key)
            grads = _faults.poison_grads(grads, step_no)
            new_params, new_opt = apply_updates(
                opt, params, grads, opt_state, lr, step_no, decay,
                row_shard=self._row_shard)
            new_params.update(bufs)
            if guard:
                new_params, new_opt, gnorm, ok = guard_select(
                    params, opt_state, new_params, new_opt, loss, grads)
                return new_params, new_opt, loss, outs, gnorm, ok
            return new_params, new_opt, loss, outs

        from ..observability import track
        return track(f"train_step:{type(self.model).__name__}",
                     jax.jit(step_sparse if sparse_specs else step,
                             donate_argnums=(0, 1)))

    def init_opt_state(self, state):
        return {k: self.optimizer.init_state(v) for k, v in state.items()
                if k in self._trainable}

    def _build_multi(self):
        """K optimizer steps per compiled call via lax.scan over stacked
        batches (leaves shaped (K, ...)).

        The TPU-native analogue of the reference's dataset trainers running
        the train loop inside the C++ executor (train_from_dataset,
        framework/trainer.h): host round-trips per step become one dispatch
        per K steps.  lr is held constant within a call (schedulers advance
        between calls)."""
        from ..optimizer.functional import apply_updates, decay_flags
        opt = self.optimizer
        trainable = self._trainable
        decay = decay_flags(opt, trainable)

        def multi(params, opt_state, step_no0, lr, rng_key, stacked):
            def body(carry, xs):
                params, opt_state, i = carry
                key = jax.random.fold_in(rng_key, i)

                def loss_of(train_params):
                    full = dict(params)
                    full.update(train_params)
                    loss, _outs, bufs = forward_loss(
                        self.model, self.loss_fn, full, xs, key,
                        self.amp_level, self.amp_dtype,
                        return_buffer_updates=True)
                    return loss, bufs

                train_params = {k: v for k, v in params.items()
                                if k in trainable}
                loss_fn = (_recompute.checkpoint(loss_of) if self._remat
                           else loss_of)
                (loss, bufs), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(train_params)
                from ..utils import faults as _faults
                grads = _faults.poison_grads(grads, step_no0 + i)
                new_params, new_opt = apply_updates(
                    opt, params, grads, opt_state, lr, step_no0 + i, decay)
                new_params.update(bufs)
                return (new_params, new_opt, i + 1), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (params, opt_state, jnp.int32(0)), stacked)
            return params, opt_state, losses

        from ..observability import track
        return track(f"train_step_multi:{type(self.model).__name__}",
                     jax.jit(multi, donate_argnums=(0, 1)))

    def _build_multi_sparse(self, example_state, example_batch_one):
        """K sparse-grad steps per compiled call: the same zeros-cotangent
        channel as the single-step sparse build, inside the lax.scan body —
        each step's RowSparseGrad feeds the lazy row-wise optimizer update,
        so the big-vocab path gets the same per-call amortization as dense
        (r3 weak #4: run_steps used to reject sparse)."""
        from ..optimizer.functional import apply_updates, decay_flags
        from ..core import selected_rows as sr
        opt = self.optimizer
        trainable = self._trainable
        decay = decay_flags(opt, trainable)

        sparse_specs, name_to_key, sparse_names = self._sparse_setup(
            example_state, example_batch_one)

        def multi(params, opt_state, step_no0, lr, rng_key, stacked):
            def body(carry, xs):
                params, opt_state, i = carry
                key = jax.random.fold_in(rng_key, i)
                zeros = {k: jnp.zeros((n, w), dt)
                         for k, (n, w, dt) in sparse_specs.items()}

                def loss_of(train_params, zvals):
                    full = dict(params)
                    full.update(train_params)
                    ctx = sr.SparseGradContext("apply", zeros=zvals,
                                               deny=self._sparse_deny)
                    with sr.use_ctx(ctx):
                        loss, _outs, bufs = forward_loss(
                            self.model, self.loss_fn, full, xs, key,
                            self.amp_level, self.amp_dtype,
                            return_buffer_updates=True)
                    return loss, (ctx.ids, bufs)

                train_params = {k: v for k, v in params.items()
                                if k in trainable and k not in sparse_names}
                loss_fn = (_recompute.checkpoint(loss_of) if self._remat
                           else loss_of)
                (loss, (ids, bufs)), (grads, zgrads) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True)(train_params,
                                                           zeros)
                grads = self._merge_sparse_grads(grads, zgrads, ids, params,
                                                 name_to_key)
                from ..utils import faults as _faults
                grads = _faults.poison_grads(grads, step_no0 + i)
                new_params, new_opt = apply_updates(
                    opt, params, grads, opt_state, lr, step_no0 + i, decay,
                    row_shard=self._row_shard)
                new_params.update(bufs)
                return (new_params, new_opt, i + 1), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (params, opt_state, jnp.int32(0)), stacked)
            return params, opt_state, losses

        from ..observability import track
        return track(f"train_step_multi:{type(self.model).__name__}",
                     jax.jit(multi, donate_argnums=(0, 1)))

    def run_steps(self, *stacked_batch):
        """Run K train steps in ONE compiled call.

        Each arg is a stacked batch whose leading axis K is the step count
        (e.g. ids of shape (K, batch, seq)).  Returns the (K,) per-step loss
        array.  Works with Embedding(sparse=True): lookup counts are baked
        per batch-shape signature, so each signature compiles its own
        multi-step program."""
        if self._guard:
            raise NotImplementedError(
                "TrainStep(guard=True) does not support run_steps: the "
                "multi-step scan has no per-step skip/rollback point (a "
                "silent bypass would apply NaN updates the guard promised "
                "to block) — use per-call steps under the guard")
        if self.accum_steps > 1:
            raise NotImplementedError(
                "TrainStep(accum_steps>1) does not support run_steps: the "
                "accumulation window already scans in-program — stack "
                "whole windows as per-call batches instead")
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        raw = tuple(unwrap(b) for b in stacked_batch)
        k_steps = raw[0].shape[0]
        if self._sparse:
            sig = ("multi",) + tuple(
                (tuple(b.shape), str(b.dtype)) for b in raw)
            self._compiled_multi = self._sig_cache.get(sig)
            if self._compiled_multi is None:
                one = tuple(b[0] for b in raw)
                self._compiled_multi = self._sig_cache[sig] = \
                    self._build_multi_sparse(state, one)
        if self._compiled_multi is None:
            self._compiled_multi = self._build_multi()
        state, self._opt_state, raw = self._place_for_row_shard(
            state, self._opt_state, raw)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no0 = jnp.asarray(self.optimizer._step_count + 1, jnp.int32)
        from ..core import rng as _rng
        rng_key = _rng.next_key()
        new_state, self._opt_state, losses = self._compiled_multi(
            state, self._opt_state, step_no0, lr, rng_key, raw)
        self.optimizer._step_count += k_steps
        sd = self.model.state_dict()
        for k, v in new_state.items():
            sd[k]._set_data(v)
        return Tensor(losses)

    def _place_for_row_shard(self, state, opt_state, raw_batch):
        """With a mesh row-sharded table among the params, every input of
        the compiled step must live on the mesh's device set (the per-shard
        update is a shard_map): replicate anything not already there.  The
        sharded table (and, after the first step, its moments) keeps its
        row sharding — device_put is skipped for leaves already on the
        mesh."""
        if not self._row_shard:
            return state, opt_state, raw_batch
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = next(iter(self._row_shard.values()))[1]
        rep = NamedSharding(mesh, P())

        def place(x):
            s = getattr(x, "sharding", None)
            if s is not None and getattr(s, "device_set", None) \
                    == rep.device_set:
                return x
            return jax.device_put(x, rep)

        return (jax.tree_util.tree_map(place, state),
                jax.tree_util.tree_map(place, opt_state),
                jax.tree_util.tree_map(place, raw_batch))

    def _ensure_compiled(self, state, batch):
        """Resolve the compiled step for this batch signature (the sparse
        path keys per shape) — shared by __call__ and warmup()."""
        if self._sparse:
            # sparse lookup counts are baked into the compiled step, so
            # each batch-shape signature needs its own build (the dense
            # path just lets jax.jit retrace)
            sig = tuple((tuple(unwrap(b).shape), str(unwrap(b).dtype))
                        for b in batch)
            self._compiled = self._sig_cache.get(sig)
            if self._compiled is None:
                self._compiled = self._sig_cache[sig] = self._build(
                    state, self._opt_state, batch)
        if self._compiled is None:
            self._compiled = self._build(state, self._opt_state, batch)
        return self._compiled

    def warmup(self, *batch) -> dict:
        """AOT-compile the step for this sample batch WITHOUT applying an
        update: params, optimizer state, BN stats and the RNG stream are
        untouched — the training analogue of `ServingEngine.warmup()`,
        so a fleet worker (or a resumed preemption victim) pays its
        compile before the first real batch instead of inside it.  With
        the persistent program store enabled (PDTPU_PROGRAM_CACHE_DIR),
        warmup in one process makes every other process's first step a
        disk hit.  Returns {'seconds', 'compiled'} — compiled=False
        means the signature was already warm (or the build is not
        AOT-compilable; the first real call then compiles normally)."""
        import time as _time
        t0 = _time.perf_counter()
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        compiled_fn = self._ensure_compiled(state, batch)
        raw_batch = tuple(unwrap(b) for b in batch)
        state, self._opt_state, raw_batch = self._place_for_row_shard(
            state, self._opt_state, raw_batch)
        did = warm_step_program(compiled_fn, state, self._opt_state,
                                self.optimizer, raw_batch)
        return {"seconds": _time.perf_counter() - t0, "compiled": did}

    def __call__(self, *batch):
        from ..observability import span as _span
        with _span("train_step"), _step_hist().time():
            return self._call_inner(*batch)

    def _call_inner(self, *batch):
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        self._ensure_compiled(state, batch)
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self.optimizer._step_count, jnp.int32)
        from ..core import rng as _rng
        rng_key = _rng.next_key()  # fresh per step: dropout masks differ
        raw_batch = tuple(unwrap(b) for b in batch)
        state, self._opt_state, raw_batch = self._place_for_row_shard(
            state, self._opt_state, raw_batch)
        out = self._compiled(
            state, self._opt_state, step_no, lr, rng_key, raw_batch)
        if self._guard:
            new_state, self._opt_state, loss, outs, gnorm, ok = out
            self.last_guard = (gnorm, ok)
        else:
            new_state, self._opt_state, loss, outs = out
        self.last_outputs = (tuple(Tensor(o) for o in outs)
                             if outs else None)
        sd = self.model.state_dict()
        for k, v in new_state.items():
            sd[k]._set_data(v)
        return Tensor(loss)

    # -- checkpointing (single-device variant of ShardedTrainStep's) ---------
    def save_checkpoint(self, directory, step=None, extra_meta=None,
                        scaler=None, data_cursor=None):
        from ..distributed import checkpoint as dck
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        if self.accum_steps > 1:
            # record the window structure: a resumed run must feed the
            # same accum_steps for the rng fold_in stream to line up
            extra_meta = dict(extra_meta or {})
            extra_meta.setdefault("accum_steps", self.accum_steps)
        return dck.save_train_state(
            directory, state, self._opt_state,
            step if step is not None else self.optimizer._step_count,
            extra_meta, optimizer=self.optimizer, scaler=scaler,
            data_cursor=data_cursor)

    def restore_checkpoint(self, directory, scaler=None):
        from ..distributed import checkpoint as dck
        res = dck.restore_sharded(directory)
        if res is None:
            return None
        meta, restored_opt = dck.apply_train_state(
            self.model, self.optimizer, res, scaler=scaler)
        fresh = self.init_opt_state(state_arrays(self.model))
        self._opt_state = dck.merge_opt_state(fresh, restored_opt)
        return meta


# ---------------------------------------------------------------------------
# save / load (inference model): AOT export via jax.export + weights pickle
# ---------------------------------------------------------------------------

def _relevant_op_versions(layer):
    """Version entries for op families this layer tree actually exercises
    (reference: op_version_registry records versions per op IN the saved
    program; embedding the full registry would make unrelated version
    bumps reject artifacts that never use the bumped op)."""
    from ..utils import op_version
    relevant = {"exported_program"}
    for _, sub in getattr(layer, "named_sublayers", lambda: [])():
        name = type(sub).__name__
        if name in ("MultiHeadAttention", "TransformerEncoderLayer",
                    "TransformerDecoderLayer", "BertLayer", "GPTBlock",
                    "ErnieLayer"):
            relevant |= {"flash_attention", "scaled_dot_product_attention"}
        if name.startswith("Quanted") or name.startswith("Int8"):
            relevant.add("fake_quantize")
        if name.startswith("BatchNorm") or name == "SyncBatchNorm":
            # conv-net blocks route through the fused epilogue family
            relevant.add("fused_bn_act")
    snap = op_version.snapshot()
    return {k: v for k, v in snap.items() if k in relevant}


def save(layer, path, input_spec=None, **config):
    """paddle.jit.save — serialize compiled fn (StableHLO via jax.export) +
    weights (reference: save_inference_model, io.py:1198)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {k: np.asarray(v) for k, v in state_arrays(layer).items()}
    np.savez(path + ".pdiparams.npz", **state)
    from ..utils import op_version
    meta = {"class": type(layer).__name__, "input_spec": None,
            "op_versions": _relevant_op_versions(layer)}
    if input_spec is not None:
        layer.eval()
        from jax import export as jax_export

        # -1/None dims export as SYMBOLIC dimensions, named by AXIS
        # POSITION ("b" for dim 0, "d<j>" otherwise) and shared across
        # inputs — so (ids, mask) specs of (-1, -1) agree on batch AND
        # seq_len, the common paddle Program -1 pattern.  Inputs whose
        # same-position dynamic dims are genuinely independent would
        # over-constrain; pass concrete sizes for those.
        scope = jax_export.SymbolicScope()

        def to_sds(s):
            if not isinstance(s, InputSpec):
                return jax.ShapeDtypeStruct(tuple(s.shape), s.dtype)
            if all(d != -1 for d in s.shape):
                return s.to_shape_dtype()
            names = ",".join(
                ("b" if j == 0 else f"d{j}") if d == -1 else str(d)
                for j, d in enumerate(s.shape))
            sym = jax_export.symbolic_shape(names, scope=scope)
            return jax.ShapeDtypeStruct(sym, s.dtype)

        specs = [to_sds(s) for s in input_spec]

        def pure(state, *args):
            return functional_call(layer, state, *args, training=False)

        state_sds = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for k, v in state.items()}
        try:
            try:
                exported = jax_export.export(jax.jit(pure))(state_sds, *specs)
            except Exception as sym_err:
                # model not shape-polymorphic: fall back to the concrete
                # export (every -1 becomes 1) rather than producing no
                # artifact — but say so, loudly and in the metadata
                import warnings
                warnings.warn(
                    "jit.save: symbolic-shape export failed "
                    f"({type(sym_err).__name__}); falling back to CONCRETE "
                    "shapes — the saved model only accepts the exact "
                    "fallback shapes (every -1 dim = 1)")
                meta["export_fallback"] = f"concrete: {sym_err}"[:500]
                specs = [s.to_shape_dtype() if isinstance(s, InputSpec)
                         else s for s in input_spec]
                exported = jax_export.export(jax.jit(pure))(state_sds, *specs)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
            meta["input_spec"] = [
                (tuple(int(d) if isinstance(d, int) else -1
                       for d in s.shape), str(np.dtype(s.dtype)))
                for s in specs]
        except Exception as e:  # export unsupported on some backends
            meta["export_error"] = str(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference artifact (reference: TranslatedLayer / AnalysisPredictor)."""

    def __init__(self, exported, state, meta=None):
        self._exported = exported
        self._state = state
        self._meta = meta or {}

    def __call__(self, *args):
        raw = tuple(unwrap(a) for a in args)
        out = self._exported.call(self._state, *raw)
        return jax.tree_util.tree_map(lambda x: Tensor(x), out)

    def eval(self):
        return self

    def train(self):
        return self


def load(path, **config):
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    from ..utils import op_version
    op_version.check_compat(meta.get("op_versions"),
                            strict=config.get("strict_op_versions", False))
    data = np.load(path + ".pdiparams.npz")
    state = {k: jnp.asarray(data[k]) for k in data.files}
    model_file = path + ".pdmodel"
    if os.path.exists(model_file):
        from jax import export as jax_export
        with open(model_file, "rb") as f:
            exported = jax_export.deserialize(f.read())
        return TranslatedLayer(exported, state, meta)
    raise FileNotFoundError(
        f"{model_file} not found — layer was saved without input_spec; "
        "load weights via paddle_tpu.load instead")


def enable_to_static(flag=True):
    pass


class ProgramTranslator:
    """API-compat shim for fluid's ProgramTranslator."""
    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag):
        pass


class TracedLayer:
    """Trace a dygraph Layer once into a compiled static callable
    (reference: fluid/dygraph/jit.py:1046 — a Program + Executor sharing
    the layer's parameters).  TPU-native: the "program" is the
    StaticFunction jit of the layer's forward; `save_inference_model`
    serializes the StableHLO artifact via `jit.save` with specs taken
    from the traced example inputs.

    Use `TracedLayer.trace(layer, inputs)`, not the constructor.
    """

    def __init__(self, layer, example_inputs, outputs):
        self._layer = layer
        self._static = StaticFunction(layer.forward, layer=layer)
        self._example = tuple(example_inputs)
        self._n_outputs = (len(outputs)
                           if isinstance(outputs, (list, tuple)) else 1)

    @staticmethod
    def trace(layer, inputs):
        """Returns (dygraph outputs, TracedLayer) like the reference."""
        ins = tuple(inputs)
        out = layer(*_wrap_args(ins))
        return out, TracedLayer(layer, ins, out)

    def __call__(self, inputs):
        """Run the compiled program on a LIST of inputs; returns the
        outputs as a list (the reference fetch-list convention)."""
        out = self._static(*inputs)
        return list(out) if isinstance(out, (list, tuple)) else [out]

    def set_strategy(self, build_strategy=None, exec_strategy=None):
        """No-op: XLA owns build/exec strategy (the reference attaches
        BuildStrategy/ExecutionStrategy to its CompiledProgram)."""

    def save_inference_model(self, path, feed=None, fetch=None, **config):
        specs = [InputSpec(tuple(unwrap(a).shape), str(unwrap(a).dtype))
                 for a in self._example]
        if feed is not None and sorted(feed) != list(range(len(specs))):
            raise NotImplementedError(
                "TracedLayer.save_inference_model: feed must cover all "
                "traced inputs (input subsetting would change the traced "
                "program)")
        if fetch is not None and sorted(fetch) != list(
                range(self._n_outputs)):
            raise NotImplementedError(
                "TracedLayer.save_inference_model: fetch must cover all "
                "traced outputs")
        save(self._layer, path, input_spec=specs, **config)


# dy2static debug logging (reference: fluid/dygraph/dygraph_to_static/
# logging_utils.py:182,221, re-exported from paddle.jit).  There is no
# source transform here (tracing IS program capture), so the knobs gate
# how loudly jit builds report: level >= 1 turns on jax compilation logs.
_VERBOSITY = 0
_CODE_LEVEL = -1
_PREV_JAX_LOG_LEVEL = None


def set_verbosity(level=0, also_to_stdout=False):
    global _VERBOSITY, _PREV_JAX_LOG_LEVEL
    import logging
    logger = logging.getLogger("jax")
    new = int(level)
    if new >= 1 and _VERBOSITY < 1:
        _PREV_JAX_LOG_LEVEL = logger.level  # restore on lowering
        logger.setLevel(logging.DEBUG)
    elif new < 1 and _VERBOSITY >= 1:
        # restore the exact saved level — 0 (NOTSET) is a valid level and
        # must round-trip, so test against None, not falsiness
        logger.setLevel(logging.WARNING if _PREV_JAX_LOG_LEVEL is None
                        else _PREV_JAX_LOG_LEVEL)
        _PREV_JAX_LOG_LEVEL = None
    _VERBOSITY = new


def get_verbosity():
    return _VERBOSITY


def set_code_level(level=100, also_to_stdout=False):
    global _CODE_LEVEL
    _CODE_LEVEL = int(level)


def get_code_level():
    return _CODE_LEVEL
