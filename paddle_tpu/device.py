"""paddle.device submodule (reference: python/paddle/device.py) — device
selection/introspection over the jax backend; implementations live in
core.device."""
from __future__ import annotations

from .core.device import (  # noqa: F401
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, is_compiled_with_xpu, get_cudnn_version,
    XPUPlace, CPUPlace, CUDAPlace, CUDAPinnedPlace,
)

__all__ = ["set_device", "get_device", "device_count",
           "is_compiled_with_cuda", "is_compiled_with_tpu",
           "is_compiled_with_xpu", "get_cudnn_version", "XPUPlace",
           "CPUPlace", "CUDAPlace", "CUDAPinnedPlace"]
