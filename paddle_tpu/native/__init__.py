"""Native (C++) runtime components.

The reference keeps its data pipeline in C++ (framework/data_feed.cc,
operators/reader/buffered_reader.cc) because Python parsing can't keep a
device fed.  Same here: `TextSlotDataFeed` wraps a multithreaded C++ reader
(src/datafeed.cc) via ctypes — built on first use with g++ (no pybind11 in
this image), cached next to the source.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "datafeed.cc")
_LIB = os.path.join(_DIR, "libpdtpu_datafeed.so")
_lock = threading.Lock()
_lib = None


class NativeBuildError(RuntimeError):
    pass


_modules = {}


def load_module(name: str) -> ctypes.CDLL:
    """Build (g++, cached by mtime) and dlopen src/<name>.cc as
    libpdtpu_<name>.so.  Generic loader for the native runtime pieces
    (datafeed keeps its original bespoke path below)."""
    with _lock:
        if name in _modules:
            return _modules[name]
        src = os.path.join(_DIR, "src", f"{name}.cc")
        lib_path = os.path.join(_DIR, f"libpdtpu_{name}.so")
        def build():
            cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", src, "-o", lib_path]
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=240)
            except (OSError, subprocess.TimeoutExpired) as e:
                raise NativeBuildError(f"g++ failed: {e}") from e
            if proc.returncode != 0:
                raise NativeBuildError(
                    f"native {name} build failed:\n{proc.stderr[-2000:]}")

        if (not os.path.exists(lib_path)
                or os.path.getmtime(lib_path) < os.path.getmtime(src)):
            build()
        try:
            lib = ctypes.CDLL(lib_path)
        except OSError:
            # e.g. an ABI-mismatched binary from another host: rebuild once.
            build()
            try:
                lib = ctypes.CDLL(lib_path)
            except OSError as e:
                raise NativeBuildError(f"dlopen {lib_path} failed: {e}") from e
        _modules[name] = lib
        return lib


def _build() -> str:
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _LIB]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=240)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"g++ unavailable or timed out: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(
            f"native datafeed build failed:\n{proc.stderr[-2000:]}")
    return _LIB


def load_library() -> ctypes.CDLL:
    """Build (if stale) and dlopen the native library."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            _build()
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build()
            try:
                lib = ctypes.CDLL(_LIB)
            except OSError as e:
                raise NativeBuildError(f"dlopen {_LIB} failed: {e}") from e
        lib.pdtpu_feed_create.restype = ctypes.c_void_p
        lib.pdtpu_feed_create.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int]
        lib.pdtpu_feed_next.restype = ctypes.c_int
        lib.pdtpu_feed_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64)]
        lib.pdtpu_feed_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


def available() -> bool:
    try:
        load_library()
        return True
    except NativeBuildError:
        return False


class TextSlotDataFeed:
    """Iterate (features, labels) numpy batches parsed by C++ worker threads.

    reference: framework/data_feed.h:117 MultiSlotDataFeed (text slots) and
    data_feed.h:302 InMemoryDataFeed.  `binary=True` reads fixed records of
    int64 label + dim float32 (the high-throughput path).
    """

    def __init__(self, files: Sequence[str], batch_size: int, dim: int,
                 n_threads: int = 2, queue_capacity: int = 8,
                 binary: bool = False, drop_last: bool = False):
        self._lib = load_library()
        self.batch_size = int(batch_size)
        self.dim = int(dim)
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self._h = self._lib.pdtpu_feed_create(
            arr, len(files), self.batch_size, self.dim, int(n_threads),
            int(queue_capacity), int(bool(binary)), int(bool(drop_last)))
        if not self._h:
            raise RuntimeError("pdtpu_feed_create failed")
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        feats = np.empty((self.batch_size, self.dim), np.float32)
        labels = np.empty((self.batch_size,), np.int64)
        n = self._lib.pdtpu_feed_next(
            self._h,
            feats.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if n == 0:
            self.close()
            raise StopIteration
        return feats[:n], labels[:n]

    def close(self):
        if not self._closed and self._h:
            self._lib.pdtpu_feed_destroy(self._h)
            self._h = None
            self._closed = True

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def write_binary_slot_file(path: str, features: np.ndarray,
                           labels: np.ndarray):
    """Helper to produce the binary record format TextSlotDataFeed reads."""
    features = np.ascontiguousarray(features, np.float32)
    labels = np.ascontiguousarray(labels, np.int64)
    assert features.ndim == 2 and len(features) == len(labels)
    with open(path, "wb") as f:
        for i in range(len(labels)):
            f.write(labels[i].tobytes())
            f.write(features[i].tobytes())
