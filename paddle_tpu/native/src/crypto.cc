// AES-CTR stream cipher for encrypted model artifacts.
//
// reference: paddle/fluid/framework/io/crypto/aes_cipher.cc (cryptopp-backed
// AES for save_inference_model encryption).  This is a self-contained
// FIPS-197 AES (128/192/256) with CTR mode — no third-party crypto library
// in the image, and CTR keeps encrypt == decrypt (one entry point).
//
// exported C ABI (ctypes):
//   int pdtpu_aes_ctr_crypt(const uint8_t* key, int key_len,
//                           const uint8_t iv[16],
//                           uint8_t* buf, long long len);
// returns 0 on success, nonzero on bad key length.

#include <cstdint>
#include <cstring>

namespace {

const uint8_t SBOX[256] = {
    0x63, 0x7c, 0x77, 0x7b, 0xf2, 0x6b, 0x6f, 0xc5, 0x30, 0x01, 0x67, 0x2b,
    0xfe, 0xd7, 0xab, 0x76, 0xca, 0x82, 0xc9, 0x7d, 0xfa, 0x59, 0x47, 0xf0,
    0xad, 0xd4, 0xa2, 0xaf, 0x9c, 0xa4, 0x72, 0xc0, 0xb7, 0xfd, 0x93, 0x26,
    0x36, 0x3f, 0xf7, 0xcc, 0x34, 0xa5, 0xe5, 0xf1, 0x71, 0xd8, 0x31, 0x15,
    0x04, 0xc7, 0x23, 0xc3, 0x18, 0x96, 0x05, 0x9a, 0x07, 0x12, 0x80, 0xe2,
    0xeb, 0x27, 0xb2, 0x75, 0x09, 0x83, 0x2c, 0x1a, 0x1b, 0x6e, 0x5a, 0xa0,
    0x52, 0x3b, 0xd6, 0xb3, 0x29, 0xe3, 0x2f, 0x84, 0x53, 0xd1, 0x00, 0xed,
    0x20, 0xfc, 0xb1, 0x5b, 0x6a, 0xcb, 0xbe, 0x39, 0x4a, 0x4c, 0x58, 0xcf,
    0xd0, 0xef, 0xaa, 0xfb, 0x43, 0x4d, 0x33, 0x85, 0x45, 0xf9, 0x02, 0x7f,
    0x50, 0x3c, 0x9f, 0xa8, 0x51, 0xa3, 0x40, 0x8f, 0x92, 0x9d, 0x38, 0xf5,
    0xbc, 0xb6, 0xda, 0x21, 0x10, 0xff, 0xf3, 0xd2, 0xcd, 0x0c, 0x13, 0xec,
    0x5f, 0x97, 0x44, 0x17, 0xc4, 0xa7, 0x7e, 0x3d, 0x64, 0x5d, 0x19, 0x73,
    0x60, 0x81, 0x4f, 0xdc, 0x22, 0x2a, 0x90, 0x88, 0x46, 0xee, 0xb8, 0x14,
    0xde, 0x5e, 0x0b, 0xdb, 0xe0, 0x32, 0x3a, 0x0a, 0x49, 0x06, 0x24, 0x5c,
    0xc2, 0xd3, 0xac, 0x62, 0x91, 0x95, 0xe4, 0x79, 0xe7, 0xc8, 0x37, 0x6d,
    0x8d, 0xd5, 0x4e, 0xa9, 0x6c, 0x56, 0xf4, 0xea, 0x65, 0x7a, 0xae, 0x08,
    0xba, 0x78, 0x25, 0x2e, 0x1c, 0xa6, 0xb4, 0xc6, 0xe8, 0xdd, 0x74, 0x1f,
    0x4b, 0xbd, 0x8b, 0x8a, 0x70, 0x3e, 0xb5, 0x66, 0x48, 0x03, 0xf6, 0x0e,
    0x61, 0x35, 0x57, 0xb9, 0x86, 0xc1, 0x1d, 0x9e, 0xe1, 0xf8, 0x98, 0x11,
    0x69, 0xd9, 0x8e, 0x94, 0x9b, 0x1e, 0x87, 0xe9, 0xce, 0x55, 0x28, 0xdf,
    0x8c, 0xa1, 0x89, 0x0d, 0xbf, 0xe6, 0x42, 0x68, 0x41, 0x99, 0x2d, 0x0f,
    0xb0, 0x54, 0xbb, 0x16};

inline uint8_t xtime(uint8_t x) {
  return static_cast<uint8_t>((x << 1) ^ ((x >> 7) * 0x1b));
}

struct AES {
  int nr;                 // rounds: 10/12/14
  uint8_t rk[15][4][4];   // round keys as state matrices (column-major)

  // key schedule (FIPS-197 §5.2)
  bool init(const uint8_t* key, int key_len) {
    int nk = key_len / 4;  // words
    if (key_len != 16 && key_len != 24 && key_len != 32) return false;
    nr = nk + 6;
    uint8_t w[60][4];
    for (int i = 0; i < nk; ++i)
      for (int j = 0; j < 4; ++j) w[i][j] = key[4 * i + j];
    uint8_t rcon = 1;
    for (int i = nk; i < 4 * (nr + 1); ++i) {
      uint8_t t[4];
      std::memcpy(t, w[i - 1], 4);
      if (i % nk == 0) {
        uint8_t tmp = t[0];  // RotWord + SubWord + Rcon
        t[0] = static_cast<uint8_t>(SBOX[t[1]] ^ rcon);
        t[1] = SBOX[t[2]];
        t[2] = SBOX[t[3]];
        t[3] = SBOX[tmp];
        rcon = xtime(rcon);
      } else if (nk > 6 && i % nk == 4) {
        for (int j = 0; j < 4; ++j) t[j] = SBOX[t[j]];
      }
      for (int j = 0; j < 4; ++j) w[i][j] = w[i - nk][j] ^ t[j];
    }
    for (int r = 0; r <= nr; ++r)
      for (int c = 0; c < 4; ++c)
        for (int j = 0; j < 4; ++j) rk[r][j][c] = w[4 * r + c][j];
    return true;
  }

  void encrypt_block(const uint8_t in[16], uint8_t out[16]) const {
    uint8_t s[4][4];
    for (int c = 0; c < 4; ++c)
      for (int r = 0; r < 4; ++r) s[r][c] = in[4 * c + r] ^ rk[0][r][c];
    for (int round = 1; round < nr; ++round) {
      uint8_t t[4][4];
      // SubBytes + ShiftRows
      for (int r = 0; r < 4; ++r)
        for (int c = 0; c < 4; ++c) t[r][c] = SBOX[s[r][(c + r) & 3]];
      // MixColumns + AddRoundKey
      for (int c = 0; c < 4; ++c) {
        uint8_t a0 = t[0][c], a1 = t[1][c], a2 = t[2][c], a3 = t[3][c];
        uint8_t x = static_cast<uint8_t>(a0 ^ a1 ^ a2 ^ a3);
        s[0][c] = static_cast<uint8_t>(a0 ^ x ^ xtime(a0 ^ a1) ^ rk[round][0][c]);
        s[1][c] = static_cast<uint8_t>(a1 ^ x ^ xtime(a1 ^ a2) ^ rk[round][1][c]);
        s[2][c] = static_cast<uint8_t>(a2 ^ x ^ xtime(a2 ^ a3) ^ rk[round][2][c]);
        s[3][c] = static_cast<uint8_t>(a3 ^ x ^ xtime(a3 ^ a0) ^ rk[round][3][c]);
      }
    }
    // final round: no MixColumns
    for (int r = 0; r < 4; ++r)
      for (int c = 0; c < 4; ++c)
        out[4 * c + r] =
            static_cast<uint8_t>(SBOX[s[r][(c + r) & 3]] ^ rk[nr][r][c]);
  }
};

}  // namespace

extern "C" {

int pdtpu_aes_ctr_crypt(const uint8_t* key, int key_len, const uint8_t* iv,
                        uint8_t* buf, long long len) {
  AES aes;
  if (!aes.init(key, key_len)) return 1;
  uint8_t ctr[16], ks[16];
  std::memcpy(ctr, iv, 16);
  for (long long off = 0; off < len; off += 16) {
    aes.encrypt_block(ctr, ks);
    long long n = len - off < 16 ? len - off : 16;
    for (long long i = 0; i < n; ++i) buf[off + i] ^= ks[i];
    for (int i = 15; i >= 0; --i)  // big-endian counter increment
      if (++ctr[i] != 0) break;
  }
  return 0;
}

// single-block ECB encrypt, exposed for known-answer tests against the
// FIPS-197 vectors from Python
int pdtpu_aes_encrypt_block(const uint8_t* key, int key_len,
                            const uint8_t* in, uint8_t* out) {
  AES aes;
  if (!aes.init(key, key_len)) return 1;
  aes.encrypt_block(in, out);
  return 0;
}

}  // extern "C"
