// C inference API — reference: paddle/fluid/inference/capi/ (pd_config.cc,
// pd_predictor.cc wrap AnalysisPredictor behind a C ABI for non-C++
// deployments) and paddle/fluid/train/demo (standalone binary embedding the
// runtime).
//
// TPU-native: the runtime is the Python/JAX world, so the C ABI embeds
// CPython and drives paddle_tpu.inference.{Config,create_predictor} over a
// jit.save artifact.  Data crosses as raw float32 buffers wrapped in
// memoryviews (np.frombuffer) — no numpy C headers needed.
//
// Build:  g++ -O2 -std=c++17 -shared -fPIC capi.cc -o libpdtpu_capi.so \
//             $(python3-config --includes) $(python3-config --ldflags --embed)
// A C consumer links the same way (see tests/capi_demo.c).

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

std::string g_last_error;

void capture_py_error(const char* where) {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = std::string(where) + ": ";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      g_last_error += PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

bool g_we_initialized = false;

struct Predictor {
  PyObject* pred;  // paddle_tpu.inference.Predictor
};

}  // namespace

extern "C" {

const char* PD_GetLastError() { return g_last_error.c_str(); }

// Initialize the embedded interpreter (no-op when already inside Python).
int PD_Init() {
  if (Py_IsInitialized()) return 0;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) {
    g_last_error = "Py_InitializeEx failed";
    return 1;
  }
  g_we_initialized = true;
  PyEval_SaveThread();  // release the GIL for PyGILState_Ensure below
  return 0;
}

void PD_Finalize() {
  if (g_we_initialized && Py_IsInitialized()) {
    PyGILState_Ensure();
    Py_Finalize();
    g_we_initialized = false;
  }
}

void* PD_CreatePredictor(const char* model_prefix) {
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod == nullptr) {
    capture_py_error("import paddle_tpu.inference");
  } else {
    PyObject* cfg = PyObject_CallMethod(mod, "Config", "(s)", model_prefix);
    if (cfg == nullptr) {
      capture_py_error("Config");
    } else {
      PyObject* pred =
          PyObject_CallMethod(mod, "create_predictor", "(N)", cfg);
      if (pred == nullptr) {
        capture_py_error("create_predictor");
      } else {
        Predictor* h = new Predictor{pred};
        result = h;
      }
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  return result;
}

// Run with one float32 input -> first float32 output.
// out_shape must hold >= 8 dims; returns 0 on success.
int PD_PredictorRun(void* handle, const float* input, const int64_t* shape,
                    int ndim, float* output, int64_t out_capacity,
                    int64_t* out_shape, int* out_ndim) {
  if (handle == nullptr) {
    g_last_error = "null predictor";
    return 1;
  }
  Predictor* h = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *np = nullptr, *arr = nullptr, *names = nullptr, *in_h = nullptr,
           *run = nullptr, *onames = nullptr, *out_h = nullptr,
           *out_arr = nullptr, *flat = nullptr;
  do {
    int64_t n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    np = PyImport_ImportModule("numpy");
    if (np == nullptr) { capture_py_error("import numpy"); break; }
    PyObject* mv = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<float*>(input)),
        n * sizeof(float), PyBUF_READ);
    if (mv == nullptr) { capture_py_error("memoryview"); break; }
    arr = PyObject_CallMethod(np, "frombuffer", "(Os)", mv, "float32");
    Py_DECREF(mv);
    if (arr == nullptr) { capture_py_error("np.frombuffer"); break; }
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
      PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* reshaped = PyObject_CallMethod(arr, "reshape", "(N)", shp);
    if (reshaped == nullptr) { capture_py_error("reshape"); break; }
    Py_DECREF(arr);
    arr = reshaped;

    names = PyObject_CallMethod(h->pred, "get_input_names", nullptr);
    if (names == nullptr) { capture_py_error("get_input_names"); break; }
    PyObject* name0 = PyList_GetItem(names, 0);  // borrowed
    in_h = PyObject_CallMethod(h->pred, "get_input_handle", "(O)", name0);
    if (in_h == nullptr) { capture_py_error("get_input_handle"); break; }
    PyObject* ok = PyObject_CallMethod(in_h, "copy_from_cpu", "(O)", arr);
    if (ok == nullptr) { capture_py_error("copy_from_cpu"); break; }
    Py_DECREF(ok);

    run = PyObject_CallMethod(h->pred, "run", nullptr);
    if (run == nullptr) { capture_py_error("run"); break; }

    onames = PyObject_CallMethod(h->pred, "get_output_names", nullptr);
    if (onames == nullptr || PyList_Size(onames) == 0) {
      capture_py_error("get_output_names");
      break;
    }
    out_h = PyObject_CallMethod(h->pred, "get_output_handle", "(O)",
                                PyList_GetItem(onames, 0));
    if (out_h == nullptr) { capture_py_error("get_output_handle"); break; }
    out_arr = PyObject_CallMethod(out_h, "copy_to_cpu", nullptr);
    if (out_arr == nullptr) { capture_py_error("copy_to_cpu"); break; }

    // shape out
    PyObject* oshape = PyObject_GetAttrString(out_arr, "shape");
    if (oshape == nullptr) { capture_py_error("out.shape"); break; }
    int on = static_cast<int>(PyTuple_Size(oshape));
    if (on > 8) on = 8;
    *out_ndim = on;
    int64_t total = 1;
    for (int i = 0; i < on; ++i) {
      out_shape[i] = PyLong_AsLongLong(PyTuple_GetItem(oshape, i));
      total *= out_shape[i];
    }
    Py_DECREF(oshape);
    if (total > out_capacity) {
      g_last_error = "output buffer too small";
      break;
    }
    // copy data: np.ascontiguousarray(out, 'float32').tobytes()
    flat = PyObject_CallMethod(np, "ascontiguousarray", "(Os)", out_arr,
                               "float32");
    if (flat == nullptr) { capture_py_error("ascontiguousarray"); break; }
    PyObject* bytes = PyObject_CallMethod(flat, "tobytes", nullptr);
    if (bytes == nullptr) { capture_py_error("tobytes"); break; }
    std::memcpy(output, PyBytes_AsString(bytes), total * sizeof(float));
    Py_DECREF(bytes);
    rc = 0;
  } while (false);
  Py_XDECREF(np);
  Py_XDECREF(arr);
  Py_XDECREF(names);
  Py_XDECREF(in_h);
  Py_XDECREF(run);
  Py_XDECREF(onames);
  Py_XDECREF(out_h);
  Py_XDECREF(out_arr);
  Py_XDECREF(flat);
  PyGILState_Release(gil);
  return rc;
}

void PD_DeletePredictor(void* handle) {
  if (handle == nullptr) return;
  Predictor* h = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(h->pred);
  PyGILState_Release(gil);
  delete h;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Standalone trainer ABI (reference: paddle/fluid/train/demo/demo_trainer.cc
// loads a saved ProgramDesc and drives the executor; here the artifact is an
// exported StableHLO train step — jit/train_export.py — and the embedded
// runtime replays it batch by batch).

namespace {
struct Trainer {
  PyObject* sess;  // paddle_tpu.jit.train_export.TrainSession
};
}  // namespace

extern "C" {

void* PD_CreateTrainer(const char* model_prefix) {
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.jit.train_export");
  if (mod == nullptr) {
    capture_py_error("import paddle_tpu.jit.train_export");
  } else {
    PyObject* sess =
        PyObject_CallMethod(mod, "TrainSession", "(s)", model_prefix);
    if (sess == nullptr) {
      capture_py_error("TrainSession");
    } else {
      result = new Trainer{sess};
    }
    Py_DECREF(mod);
  }
  PyGILState_Release(gil);
  return result;
}

// One optimizer step on a float32 feature buffer + int64 label buffer.
// Returns 0 on success and writes the step's loss.
int PD_TrainerStep(void* handle, const float* feats, const int64_t* fshape,
                   int fndim, const int64_t* labels, const int64_t* lshape,
                   int lndim, float* loss_out) {
  if (handle == nullptr) {
    g_last_error = "null trainer";
    return 1;
  }
  Trainer* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = 1;
  PyObject *np = nullptr, *farr = nullptr, *larr = nullptr, *loss = nullptr;
  do {
    np = PyImport_ImportModule("numpy");
    if (np == nullptr) { capture_py_error("import numpy"); break; }
    int64_t fn = 1, ln = 1;
    for (int i = 0; i < fndim; ++i) fn *= fshape[i];
    for (int i = 0; i < lndim; ++i) ln *= lshape[i];

    PyObject* fmv = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<float*>(feats)),
        fn * sizeof(float), PyBUF_READ);
    if (fmv == nullptr) { capture_py_error("feat memoryview"); break; }
    farr = PyObject_CallMethod(np, "frombuffer", "(Os)", fmv, "float32");
    Py_DECREF(fmv);
    if (farr == nullptr) { capture_py_error("np.frombuffer feats"); break; }
    PyObject* fshp = PyTuple_New(fndim);
    for (int i = 0; i < fndim; ++i)
      PyTuple_SET_ITEM(fshp, i, PyLong_FromLongLong(fshape[i]));
    PyObject* fre = PyObject_CallMethod(farr, "reshape", "(N)", fshp);
    if (fre == nullptr) { capture_py_error("reshape feats"); break; }
    Py_DECREF(farr);
    farr = fre;

    PyObject* lmv = PyMemoryView_FromMemory(
        reinterpret_cast<char*>(const_cast<int64_t*>(labels)),
        ln * sizeof(int64_t), PyBUF_READ);
    if (lmv == nullptr) { capture_py_error("label memoryview"); break; }
    larr = PyObject_CallMethod(np, "frombuffer", "(Os)", lmv, "int64");
    Py_DECREF(lmv);
    if (larr == nullptr) { capture_py_error("np.frombuffer labels"); break; }
    PyObject* lshp = PyTuple_New(lndim);
    for (int i = 0; i < lndim; ++i)
      PyTuple_SET_ITEM(lshp, i, PyLong_FromLongLong(lshape[i]));
    PyObject* lre = PyObject_CallMethod(larr, "reshape", "(N)", lshp);
    if (lre == nullptr) { capture_py_error("reshape labels"); break; }
    Py_DECREF(larr);
    larr = lre;

    loss = PyObject_CallMethod(t->sess, "step", "(OO)", farr, larr);
    if (loss == nullptr) { capture_py_error("TrainSession.step"); break; }
    *loss_out = static_cast<float>(PyFloat_AsDouble(loss));
    if (PyErr_Occurred()) { capture_py_error("loss to float"); break; }
    rc = 0;
  } while (false);
  Py_XDECREF(np);
  Py_XDECREF(farr);
  Py_XDECREF(larr);
  Py_XDECREF(loss);
  PyGILState_Release(gil);
  return rc;
}

void PD_DeleteTrainer(void* handle) {
  if (handle == nullptr) return;
  Trainer* t = static_cast<Trainer*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(t->sess);
  PyGILState_Release(gil);
  delete t;
}

}  // extern "C"
