// Native data-feed engine.
//
// TPU-native equivalent of the reference's C++ input pipeline:
//   - paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed: worker threads
//     parse text slot files into feed tensors)
//   - paddle/fluid/operators/reader/buffered_reader.cc (double-buffered
//     prefetch queue decoupling host parsing from device consumption)
//
// Design: N reader threads stream assigned files, parse records into fixed
// -shape batch buffers, and push them into a bounded ring queue.  The Python
// side (paddle_tpu.native.TextSlotDataFeed) pops ready batches zero-copy
// into numpy via ctypes.  Formats:
//   text:   one sample per line: "<label>\t<f0>,<f1>,...,<fD-1>"
//   binary: fixed records: int64 label + D float32 features, little-endian
//
// Build: g++ -O3 -shared -fPIC -pthread (see paddle_tpu/native/__init__.py).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fstream>
#include <memory>
#include <mutex>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Batch {
  std::vector<float> feats;     // batch_size * dim
  std::vector<int64_t> labels;  // batch_size
  int rows = 0;
};

class BoundedQueue {
 public:
  explicit BoundedQueue(size_t cap) : cap_(cap) {}

  void Push(std::unique_ptr<Batch> b) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < cap_ || closed_; });
    if (closed_) return;
    q_.push_back(std::move(b));
    not_empty_.notify_one();
  }

  // Returns nullptr when the queue is closed and drained.
  std::unique_ptr<Batch> Pop() {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || (closed_ && done_); });
    if (q_.empty()) return nullptr;
    auto b = std::move(q_.front());
    q_.pop_front();
    not_full_.notify_one();
    return b;
  }

  void CloseWhenDone() {  // producers finished
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    closed_ = true;
    not_empty_.notify_all();
    not_full_.notify_all();
  }

  void Abort() {  // consumer going away: unblock producers
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    done_ = true;
    q_.clear();
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  size_t cap_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::deque<std::unique_ptr<Batch>> q_;
  bool closed_ = false;
  bool done_ = false;
};

class DataFeed {
 public:
  DataFeed(std::vector<std::string> files, int batch_size, int dim,
           int n_threads, int queue_cap, bool binary, bool drop_last)
      : files_(std::move(files)),
        batch_size_(batch_size),
        dim_(dim),
        binary_(binary),
        drop_last_(drop_last),
        queue_(queue_cap > 0 ? queue_cap : 8) {
    next_file_.store(0);
    active_.store(n_threads > 0 ? n_threads : 1);
    int nt = n_threads > 0 ? n_threads : 1;
    for (int i = 0; i < nt; ++i) {
      threads_.emplace_back([this] { Worker(); });
    }
  }

  ~DataFeed() {
    queue_.Abort();
    for (auto& t : threads_) {
      if (t.joinable()) t.join();
    }
  }

  // Returns rows copied (0 = exhausted).
  int Next(float* out_feats, int64_t* out_labels) {
    auto b = queue_.Pop();
    if (!b) return 0;
    std::memcpy(out_feats, b->feats.data(),
                sizeof(float) * size_t(b->rows) * dim_);
    std::memcpy(out_labels, b->labels.data(), sizeof(int64_t) * b->rows);
    return b->rows;
  }

 private:
  void EmitRow(Batch* cur, const float* feats, int64_t label) {
    std::memcpy(cur->feats.data() + size_t(cur->rows) * dim_, feats,
                sizeof(float) * dim_);
    cur->labels[cur->rows] = label;
    ++cur->rows;
  }

  std::unique_ptr<Batch> NewBatch() const {
    auto b = std::make_unique<Batch>();
    b->feats.resize(size_t(batch_size_) * dim_);
    b->labels.resize(batch_size_);
    return b;
  }

  void Worker() {
    std::vector<float> row(dim_);
    auto cur = NewBatch();
    for (;;) {
      size_t fi = next_file_.fetch_add(1);
      if (fi >= files_.size()) break;
      if (binary_) {
        ReadBinary(files_[fi], &cur);
      } else {
        ReadText(files_[fi], &cur, &row);
      }
    }
    if (cur->rows > 0 && !drop_last_) queue_.Push(std::move(cur));
    if (active_.fetch_sub(1) == 1) queue_.CloseWhenDone();
  }

  void ReadText(const std::string& path, std::unique_ptr<Batch>* cur,
                std::vector<float>* row) {
    std::ifstream in(path);
    if (!in) {
      std::fprintf(stderr, "[pdtpu datafeed] cannot open %s\n", path.c_str());
      return;
    }
    std::string line;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      const char* p = line.c_str();
      char* end = nullptr;
      int64_t label = std::strtoll(p, &end, 10);
      if (end == p) continue;  // malformed label: skip line
      p = (*end == '\t' || *end == ' ') ? end + 1 : end;
      int d = 0;
      while (d < dim_ && *p) {
        (*row)[d++] = std::strtof(p, &end);
        if (end == p) break;
        p = (*end == ',') ? end + 1 : end;
      }
      if (d != dim_) continue;  // malformed feature count: skip line
      EmitRow(cur->get(), row->data(), label);
      if ((*cur)->rows == batch_size_) {
        queue_.Push(std::move(*cur));
        *cur = NewBatch();
      }
    }
  }

  void ReadBinary(const std::string& path, std::unique_ptr<Batch>* cur) {
    std::ifstream in(path, std::ios::binary);
    if (!in) {
      std::fprintf(stderr, "[pdtpu datafeed] cannot open %s\n", path.c_str());
      return;
    }
    const size_t rec = sizeof(int64_t) + sizeof(float) * dim_;
    std::vector<char> buf(rec);
    while (in.read(buf.data(), rec)) {
      int64_t label;
      std::memcpy(&label, buf.data(), sizeof(int64_t));
      EmitRow(cur->get(),
              reinterpret_cast<const float*>(buf.data() + sizeof(int64_t)),
              label);
      if ((*cur)->rows == batch_size_) {
        queue_.Push(std::move(*cur));
        *cur = NewBatch();
      }
    }
  }

  std::vector<std::string> files_;
  const int batch_size_;
  const int dim_;
  const bool binary_;
  const bool drop_last_;
  BoundedQueue queue_;
  std::vector<std::thread> threads_;
  std::atomic<size_t> next_file_;
  std::atomic<int> active_;
};

}  // namespace

extern "C" {

void* pdtpu_feed_create(const char** files, int nfiles, int batch_size,
                        int dim, int n_threads, int queue_cap, int binary,
                        int drop_last) {
  std::vector<std::string> fs(files, files + nfiles);
  return new DataFeed(std::move(fs), batch_size, dim, n_threads, queue_cap,
                      binary != 0, drop_last != 0);
}

int pdtpu_feed_next(void* h, float* out_feats, int64_t* out_labels) {
  return static_cast<DataFeed*>(h)->Next(out_feats, out_labels);
}

void pdtpu_feed_destroy(void* h) { delete static_cast<DataFeed*>(h); }

}  // extern "C"
