"""paddle.inference — Config / Predictor deployment API.

Reference: paddle/fluid/inference/api/paddle_inference_api.h
(AnalysisConfig + AnalysisPredictor + ZeroCopyTensor): configure a saved
model, create a predictor, feed named input handles, run, read named
output handles.

TPU-native: the "engine" is the exported StableHLO program saved by
`paddle.jit.save` — deserialized once and executed by the JAX runtime.
The reference's pass/optimization knobs (ir_optim, memory_optim, mkldnn,
TensorRT) are accepted for API compatibility and recorded, but they are
subsumed by XLA compilation: there is no separate pass pipeline to
toggle.  `enable_profile` wires the paddle_tpu profiler around `run()`.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

__all__ = ["Config", "Predictor", "PredictorTensor", "ServingPredictor",
           "create_predictor"]


class Config:
    """AnalysisConfig equivalent (reference: paddle_inference_api.h)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        # paddle convention: prog file "model.pdmodel" + "model.pdiparams";
        # here one artifact prefix covers both (jit.save layout)
        self._prefix = None
        if model_path is not None:
            self.set_model(model_path, params_path)
        self._ir_optim = True
        self._memory_optim = False
        self._profile = False
        self._device = "tpu"
        self._threads = 1
        self._serving = None
        self._recsys = None

    # -- model ----------------------------------------------------------------
    def set_model(self, model_path: str, params_path: Optional[str] = None):
        for suffix in (".pdmodel", ".pdiparams", ".pdiparams.npz"):
            if model_path.endswith(suffix):
                model_path = model_path[:-len(suffix)]
                break
        if params_path is not None:
            # jit.save artifacts keep program+weights under one prefix; a
            # divergent params location cannot be honored — fail loudly
            # instead of silently loading from a path the user never gave
            expect = model_path + ".pdiparams"
            stripped = params_path[:-4] if params_path.endswith(".npz") \
                else params_path
            if stripped != expect:
                raise ValueError(
                    f"params_path {params_path!r} disagrees with the "
                    f"artifact prefix {model_path!r} (expected "
                    f"{expect}[.npz]); paddle_tpu artifacts store weights "
                    "next to the program")
        self._prefix = model_path

    def model_dir(self):
        return self._prefix

    # -- device ---------------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device = "gpu"  # recorded; execution uses the JAX backend

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "xpu"

    def use_gpu(self):
        return self._device == "gpu"

    def set_cpu_math_library_num_threads(self, n: int):
        self._threads = int(n)

    # -- optimization knobs (XLA-subsumed, recorded for compat) --------------
    def switch_ir_optim(self, flag: bool = True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag: bool = True):
        self._memory_optim = bool(flag)

    def memory_optim_enabled(self):
        return self._memory_optim

    def enable_tensorrt_engine(self, *a, **k):
        pass  # XLA is the backend; accepted for API compat

    def enable_mkldnn(self):
        pass

    def switch_use_feed_fetch_ops(self, flag=False):
        pass

    def switch_specify_input_names(self, flag=True):
        pass

    # -- serving mode ---------------------------------------------------------
    def enable_serving(self, model=None, model_provider=None, **engine_opts):
        """Switch create_predictor() to the continuous-batching
        ServingEngine (paddle_tpu.serving).

        Exactly one of:
          model           an in-memory Layer implementing the
                          gen_fixed_cache/forward_fixed protocol
          model_provider  zero-arg callable building such a Layer; its
                          weights are then restored from this Config's
                          jit.save artifact (`<prefix>.pdiparams.npz`) —
                          the serving analogue of loading a saved model

        engine_opts pass through to ServingEngine (max_slots, max_len,
        prefill_buckets, max_queue_depth, pad_token_id, dtype,
        draft_model, spec_tokens, and the distributed-serving knobs:
        kv="paged" + block_size/num_blocks for the block-granular KV
        pool, mesh= for the tensor-parallel engine — see the README
        "Distributed serving" section).

        `quantize="int8"` converts the model (and the draft model, when
        one is configured) with `quantization.quantize_for_serving`
        before the engine is built: int8 weight-only Linears with
        per-channel fp scales, dequantized at use inside the UNCHANGED
        serving programs — no new compiled programs beyond the quantized
        set, half/quarter the weight HBM per decode step.

        `draft_model=` (a smaller Layer speaking the same fixed-cache
        protocol) turns on speculative decoding: the draft proposes
        `spec_tokens` tokens per tick and the target verifies them in one
        batched forward; greedy streams stay bit-identical to solo
        generate.  See the README "Speculative + quantized decoding"
        section.

        `gateway=` additionally fronts the engine with the multi-tenant
        SLO-aware ServingGateway (per-tenant rate limits + weighted
        fairness, priority preemption with KV save/restore, load
        shedding, OpenAI-shaped HTTP endpoint).  Pass True for defaults,
        or a dict of ServingGateway kwargs (tenants=, shed=, preempt=,
        model_name=, ...).  The predictor then routes submit() through
        the gateway (tenant=/priority= become available) and the gateway
        drives the engine loop.

        Program lifecycle (README "Program lifecycle"):
        `program_cache_dir=` enables the persistent program store for
        this process (same as PDTPU_PROGRAM_CACHE_DIR) so every compile
        — eager dispatch, warmup, serving programs — reads/writes the
        shared on-disk cache.  `program_set=` boots the engine from an
        AOT program-set artifact (`predictor.save_program_set(path)` /
        `ServingEngine.save_program_set`) WITHOUT retracing any model
        code; a stale or corrupt artifact is rejected with a warning
        (counted as ``program_set_fallback_total``) and the engine falls
        back to a fresh trace+compile — never silent reuse.
        """
        if (model is None) == (model_provider is None):
            raise ValueError(
                "enable_serving needs exactly one of model= (in-memory) or "
                "model_provider= (architecture factory for a saved "
                "artifact)")
        self._serving = {"model": model, "model_provider": model_provider,
                         **engine_opts}

    def serving_enabled(self) -> bool:
        return self._serving is not None

    def enable_recsys_serving(self, model=None, table=None, offsets=None,
                              **opts):
        """Switch create_predictor() to the batched deduped-lookup recsys
        scorer (embedding.RecsysPredictor).

        model    an external-embedding-mode Layer (e.g. models.DLRM with
                 embedding="external"): forward(dense, emb_rows)
        table    the row store — embedding.HostEmbeddingTable or a raw
                 (rows, dim) ndarray (host-resident: bigger than device
                 memory is the point)
        offsets  per-feature offsets into the concatenated table
                 (models.DLRMConfig.offsets)

        opts pass through to RecsysPredictor (max_batch, window_ms,
        max_queue, slab_bucket).  Concurrent submit()s are merged into one
        forward with ONE id-dedup + row fetch across all of them; a full
        queue rejects with a typed terminal response — the PR-6 gateway's
        admission contract applied to scoring traffic.
        """
        if model is None or table is None:
            raise ValueError(
                "enable_recsys_serving needs model= (external-embedding "
                "Layer) and table= (HostEmbeddingTable or ndarray)")
        self._recsys = {"model": model, "table": table, "offsets": offsets,
                        **opts}

    def recsys_enabled(self) -> bool:
        return self._recsys is not None

    # -- profiling ------------------------------------------------------------
    def enable_profile(self):
        self._profile = True

    def summary(self) -> str:
        return (f"Config(model={self._prefix!r}, device={self._device}, "
                f"ir_optim={self._ir_optim}, "
                f"memory_optim={self._memory_optim}, "
                f"threads={self._threads}, "
                f"serving={self.serving_enabled()})")


class PredictorTensor:
    """ZeroCopyTensor equivalent: a named input/output slot."""

    def __init__(self, name: str, shape=None, dtype=None):
        self.name = name
        self._shape = tuple(shape) if shape is not None else None
        self._dtype = dtype
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr: np.ndarray):
        arr = np.asarray(arr)
        if self._dtype is not None:
            arr = arr.astype(self._dtype, copy=False)
        self._value = arr

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"tensor {self.name!r} has no value yet "
                               "(run() first)")
        return np.asarray(self._value)

    def reshape(self, shape):
        self._shape = tuple(shape)

    def shape(self):
        if self._value is not None:
            return list(self._value.shape)
        return list(self._shape) if self._shape else []


class Predictor:
    """AnalysisPredictor equivalent over a jit.save artifact."""

    def __init__(self, config: Config):
        from ..jit import load as jit_load
        if config.model_dir() is None:
            raise ValueError("Config has no model path (set_model)")
        self._config = config
        self._layer = jit_load(config.model_dir())
        specs = (self._layer._meta or {}).get("input_spec") or []
        if not specs:
            raise RuntimeError(
                "artifact has no input_spec metadata; re-export it with "
                "paddle.jit.save(..., input_spec=[...])")
        self._inputs: Dict[str, PredictorTensor] = {
            f"x{i}": PredictorTensor(f"x{i}", shape, dtype)
            for i, (shape, dtype) in enumerate(specs)}
        self._outputs: Dict[str, PredictorTensor] = {}

    def get_input_names(self) -> List[str]:
        return list(self._inputs)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return self._inputs[name]

    def run(self) -> bool:
        from ..utils.monitor import stat_add
        stat_add("STAT_predictor_runs")
        args = []
        for name, t in self._inputs.items():
            if t._value is None:
                raise RuntimeError(f"input {name!r} not set")
            args.append(t._value)
        prof = None
        if self._config._profile:
            from ..utils import profiler
            prof = profiler.RecordEvent("predictor_run")
            prof.__enter__()
        try:
            out = self._layer(*args)
        finally:
            if prof is not None:
                prof.__exit__(None, None, None)
        leaves = jax.tree_util.tree_leaves(out)
        self._outputs = {}
        for i, leaf in enumerate(leaves):
            t = PredictorTensor(f"out{i}")
            t.copy_from_cpu(np.asarray(
                leaf.numpy() if hasattr(leaf, "numpy") else leaf))
            self._outputs[t.name] = t
        return True

    def get_output_names(self) -> List[str]:
        return list(self._outputs)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return self._outputs[name]

    def profile_report(self) -> Dict:
        """One coherent report for the one-shot predictor: the Config's
        accepted-but-recorded knobs (ir_optim, memory_optim, threads)
        alongside profiler op spans and monitor counters — the same shape
        ServingPredictor.profile_report() returns for serving mode."""
        return _profile_report(self._config)


def _profile_report(config: Config, serving_metrics=None) -> Dict:
    from .. import observability
    from ..utils import profiler
    from ..utils.monitor import stats
    rep = {
        "config": {"model": config._prefix, "device": config._device,
                   "ir_optim": config._ir_optim,
                   "memory_optim": config._memory_optim,
                   "threads": config._threads,
                   "profile": config._profile},
        "op_spans": profiler.summary(),
        "stats": {k: v for k, v in stats().items()
                  if k.startswith("STAT_serving_")
                  or k == "STAT_predictor_runs"},
        # the unified telemetry report (PR 5): dispatch cache, dataloader,
        # checkpoint, train, serving histograms, compiled programs — the
        # same shape observability.report() returns everywhere else
        "observability": observability.report(),
    }
    if serving_metrics is not None:
        rep["serving"] = serving_metrics
    return rep


class ServingPredictor:
    """Serving-mode predictor: create_predictor(config) returns this when
    `config.enable_serving(...)` was called.  Wraps a running
    paddle_tpu.serving.ServingEngine (background loop started, programs
    precompiled) behind the predictor surface."""

    def __init__(self, config: Config):
        from ..serving import ServingEngine
        opts = dict(config._serving)
        model = opts.pop("model", None)
        provider = opts.pop("model_provider", None)
        warmup = opts.pop("warmup", True)
        start = opts.pop("start", True)
        gateway = opts.pop("gateway", None)
        quantize = opts.pop("quantize", None)
        program_cache_dir = opts.pop("program_cache_dir", None)
        if program_cache_dir is not None:
            from ..programs import store as _pstore
            _pstore.enable(program_cache_dir)
        if model is None:
            model = provider()
            prefix = config.model_dir()
            if prefix is None:
                raise ValueError(
                    "serving with model_provider= needs a jit.save artifact "
                    "(Config.set_model) to restore weights from")
            data = np.load(prefix + ".pdiparams.npz")
            model.set_state_dict({k: data[k] for k in data.files})
        model.eval()
        draft = opts.get("draft_model")
        if draft is not None:
            draft.eval()
        if quantize is not None:
            # int8 weight-only conversion at deployment: the fp weights
            # (in-memory or restored from the artifact) become int8
            # buffers + scales BEFORE any serving program traces, so the
            # compiled set holds int8 from the first compile
            from ..quantization import quantize_for_serving
            model = quantize_for_serving(model, quantize)
            if draft is not None:
                opts["draft_model"] = quantize_for_serving(draft, quantize)
        self._config = config
        try:
            self.engine = ServingEngine(model, profile=config._profile,
                                        **opts)
        except Exception as e:
            from ..programs.program_set import ProgramSetError
            if not isinstance(e, ProgramSetError) or "program_set" not in opts:
                raise
            # a stale/corrupt AOT program set must cost a recompile, not
            # an outage: warn loudly, count it, trace fresh
            import warnings
            warnings.warn(
                f"enable_serving(program_set=...): artifact rejected "
                f"({e}); falling back to a fresh trace+compile")
            try:
                from ..observability.metrics import counter
                counter("program_set_fallback_total",
                        "serving boots that rejected their AOT program "
                        "set and fell back to tracing").inc()
            except Exception:
                pass
            opts.pop("program_set", None)
            self.engine = ServingEngine(model, profile=config._profile,
                                        **opts)
        if warmup:
            self.engine.warmup()
        self.gateway = None
        if gateway is not None and gateway is not False:
            from ..serving import ServingGateway
            gw_opts = {} if gateway is True else dict(gateway)
            self.gateway = ServingGateway(self.engine, **gw_opts)
        if start:
            # the gateway owns the engine loop when present (preemption
            # must interleave with engine steps on one thread)
            if self.gateway is not None:
                self.gateway.start()
            else:
                self.engine.start()

    def submit(self, prompt, max_new_tokens, **kwargs):
        """Enqueue a request; returns the streaming serving.Response.
        With a gateway configured, kwargs additionally accept tenant= and
        priority= and every admission outcome is a terminal Response
        (shed/rate-limited requests come back already failed instead of
        raising)."""
        if self.gateway is not None:
            return self.gateway.submit(prompt, max_new_tokens, **kwargs)
        return self.engine.submit(prompt, max_new_tokens, **kwargs)

    def metrics(self):
        if self.gateway is not None:
            return self.gateway.metrics()
        return self.engine.metrics()

    def save_program_set(self, path: str,
                         extra_meta: Optional[dict] = None) -> str:
        """Export the engine's whole compiled-program family as one AOT
        artifact (see README "Program lifecycle"); other replicas boot
        from it via ``enable_serving(..., program_set=path)`` without
        retracing."""
        return self.engine.save_program_set(path, extra_meta)

    def serve_http(self, port: int = 8000, addr: str = "127.0.0.1"):
        """Start the OpenAI-shaped streaming endpoint over the gateway
        (requires gateway= in enable_serving); returns the server."""
        if self.gateway is None:
            raise ValueError(
                "serve_http needs a gateway: enable_serving(..., "
                "gateway=True) or gateway={...}")
        from ..serving import serve_gateway
        return serve_gateway(self.gateway, port=port, addr=addr)

    def profile_report(self) -> Dict:
        """Config knobs + profiler spans + live serving metrics in one
        report (enable_profile additionally records serving_prefill /
        serving_decode spans in the profiler table)."""
        rep = _profile_report(self._config, self.engine.metrics())
        if self.gateway is not None:
            gm = self.gateway.metrics()
            gm.pop("engine", None)  # already under rep["serving"]
            rep["gateway"] = gm
        return rep

    def close(self):
        if self.gateway is not None:
            self.gateway.close()  # closes the engine too
        else:
            self.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def create_predictor(config: Config):
    if config.recsys_enabled():
        from ..embedding import RecsysPredictor
        return RecsysPredictor(**config._recsys)
    if config.serving_enabled():
        return ServingPredictor(config)
    return Predictor(config)
