"""C inference API build + ctypes bindings.

Reference: paddle/fluid/inference/capi/ (C ABI over AnalysisPredictor, used
from Go/R/C deployments) and paddle/fluid/train/demo/ (standalone binary
embedding the runtime).  The C surface lives in native/src/capi.cc; this
module builds it (needs libpython, via python3-config) and exposes a ctypes
client used by the tests — external C programs link the same .so directly.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from ..native import NativeBuildError

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_DIR, "native", "src", "capi.cc")
_LIB = os.path.join(_DIR, "native", "libpdtpu_capi.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def embed_flags() -> Tuple[list, list]:
    """(include flags, link flags) for embedding CPython."""
    inc = ["-I" + sysconfig.get_paths()["include"]]
    ver = sysconfig.get_config_var("LDVERSION") or sysconfig.get_config_var(
        "VERSION")
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    link = [f"-L{libdir}", f"-lpython{ver}"]
    return inc, link


def build() -> str:
    inc, link = embed_flags()
    cmd = (["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC,
            "-o", _LIB] + inc + link)
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise NativeBuildError(
            f"capi build failed:\n{proc.stderr[-2000:]}")
    return _LIB


def load_library() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            build()
        lib = ctypes.CDLL(_LIB, mode=ctypes.RTLD_GLOBAL)
        lib.PD_Init.restype = ctypes.c_int
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_PredictorRun.restype = ctypes.c_int
        lib.PD_PredictorRun.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int)]
        lib.PD_DeletePredictor.argtypes = [ctypes.c_void_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        _lib = lib
        return lib


def available() -> bool:
    try:
        load_library()
        return True
    except NativeBuildError:
        return False


class CPredictor:
    """ctypes client over the C ABI (what a Go/R binding would wrap)."""

    def __init__(self, model_prefix: str):
        self._lib = load_library()
        self._lib.PD_Init()
        self._h = self._lib.PD_CreatePredictor(model_prefix.encode())
        if not self._h:
            raise RuntimeError(
                f"PD_CreatePredictor: "
                f"{self._lib.PD_GetLastError().decode()}")

    def run(self, arr: np.ndarray,
            out_capacity: int = 1 << 22) -> np.ndarray:
        arr = np.ascontiguousarray(arr, np.float32)
        shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
        out = np.empty((out_capacity,), np.float32)
        out_shape = (ctypes.c_int64 * 8)()
        out_ndim = ctypes.c_int()
        rc = self._lib.PD_PredictorRun(
            self._h, arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            shape, arr.ndim,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out_capacity, out_shape, ctypes.byref(out_ndim))
        if rc != 0:
            raise RuntimeError(
                f"PD_PredictorRun: {self._lib.PD_GetLastError().decode()}")
        dims = tuple(out_shape[i] for i in range(out_ndim.value))
        n = int(np.prod(dims)) if dims else 1
        return out[:n].reshape(dims).copy()

    def close(self):
        if self._h:
            self._lib.PD_DeletePredictor(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
