"""paddle.framework equivalents: save/load (filled out in utils/checkpoint)."""
def save(obj, path, protocol=4, **kwargs):
    from .utils.checkpoint import save as _save
    return _save(obj, path, protocol, **kwargs)

def load(path, **kwargs):
    from .utils.checkpoint import load as _load
    return _load(path, **kwargs)
