"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (~2.0), re-designed for JAX/XLA/pallas.

Design (see SURVEY.md §7): one world instead of the reference's two —
eager ops are jit-able traced ops, autograd is a tape over jax.vjp,
parallelism is mesh + sharding specs instead of program rewriting.
"""
from __future__ import annotations

__version__ = "0.1.0"

import warnings as _warnings
# jax runs x32 by default (the right call on TPU); paddle-style int64/float64
# requests silently narrow — suppress the per-call warning noise.
_warnings.filterwarnings(
    "ignore", message="Explicitly requested dtype.*truncated.*")

from .core.tensor import (Tensor, Parameter, no_grad, enable_grad,  # noqa: F401
                          is_grad_enabled, set_grad_enabled)
from .core.device import (CPUPlace, CUDAPlace, TPUPlace, XPUPlace,  # noqa: F401
                          CUDAPinnedPlace, set_device, get_device,
                          device_count, is_compiled_with_cuda,
                          is_compiled_with_tpu, is_compiled_with_xpu,
                          get_cudnn_version)
from .core.dtype import set_default_dtype, get_default_dtype  # noqa: F401
from .core.rng import seed, get_rng_state, set_rng_state  # noqa: F401
# accelerator rng-state aliases (paddle.get/set_cuda_rng_state): one PRNG
# stream serves every backend in the jax design
from .core.rng import (get_rng_state as get_cuda_rng_state,  # noqa: F401
                       set_rng_state as set_cuda_rng_state)
from .core.tape import grad  # noqa: F401

# dtype name aliases (paddle.float32 etc.)
import jax.numpy as _jnp
float16 = _jnp.float16
bfloat16 = _jnp.bfloat16
float32 = _jnp.float32
float64 = _jnp.float64
int8 = _jnp.int8
int16 = _jnp.int16
int32 = _jnp.int32
int64 = _jnp.int64
uint8 = _jnp.uint8
bool = _jnp.bool_  # noqa: A001
complex64 = _jnp.complex64
complex128 = _jnp.complex128

from .tensor import *  # noqa: F401,F403  (to_tensor, ones, matmul, ...)
from . import tensor  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import metric  # noqa: F401
from . import distribution  # noqa: F401
from . import vision  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model, summary, flops  # noqa: F401
from . import distributed  # noqa: F401
from .framework import save, load  # noqa: F401
from . import utils  # noqa: F401
from . import ops  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import observability  # noqa: F401
from . import programs  # noqa: F401
programs.bootstrap()  # PDTPU_PROGRAM_CACHE_DIR: persistent program store
from . import onnx  # noqa: F401
from .nn.layer_base import ParamAttr  # noqa: F401
from .distributed.parallel_layer import DataParallel  # noqa: F401
from .hapi import callbacks  # noqa: F401


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """paddle.set_printoptions — numpy print options drive Tensor repr."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)

disable_static = lambda *a, **k: None  # noqa: E731  (always "dygraph")
enable_static = lambda *a, **k: None  # noqa: E731


def in_dynamic_mode():
    return True
from . import generation  # noqa: F401,E402
from . import serving  # noqa: F401,E402
from . import embedding  # noqa: F401,E402
from .compat import (tensordot, has_inf, has_nan,  # noqa: F401,E402
                     elementwise_floordiv, elementwise_mod, elementwise_pow,
                     reduce_max, reduce_min, reduce_mean, reduce_prod,
                     reduce_sum, fill_constant, create_global_var, data,
                     LoDTensor, LoDTensorArray,
                     get_tensor_from_selected_rows,
                     monkey_patch_math_varbase, monkey_patch_variable,
                     crop_tensor, enable_dygraph, disable_dygraph,
                     in_dygraph_mode)
VarBase = Tensor  # fluid-era Tensor name
from . import version  # noqa: E402
from .version import full_version  # noqa: F401,E402
commit = version.commit
from . import incubate  # noqa: F401,E402
from . import device  # noqa: E402  (module wins over the function imports)
from . import sysconfig  # noqa: F401,E402
from .batch import batch  # noqa: F401,E402
from . import fluid  # noqa: F401,E402  (wholesale `from paddle import fluid`)
