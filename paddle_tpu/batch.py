"""paddle.batch (reference: python/paddle/batch.py:18) — wrap a sample
reader into a batched reader.  Pure-Python iterator plumbing; the
device-side path is io.DataLoader."""
from __future__ import annotations

__all__ = ["batch"]


def batch(reader, batch_size, drop_last=False):
    """Returns a reader yielding lists of `batch_size` samples from
    `reader` (a callable returning an iterable); the short final batch is
    kept unless drop_last."""
    batch_size = int(batch_size)
    if batch_size <= 0:
        raise ValueError(
            f"batch_size should be a positive integer, got {batch_size}")

    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
