"""Control-flow API: while_loop / cond / case / switch_case.

Reference: python/paddle/fluid/layers/control_flow.py:1 (While/Switch ops
backed by operators/controlflow/while_op.cc, conditional_block_op.cc).  The
reference builds sub-block programs and schedules them with a C++ executor;
here the same signatures map onto the two native execution modes:

- EAGER (concrete predicate): the chosen branch / loop body runs directly
  as ordinary dispatched ops, so everything records on the tape and
  `backward()` differentiates through it — dynamic trip counts included
  (this is what the reference's dygraph path does too: fluid/dygraph
  control flow is plain Python).
- TRACED (inside jit / TrainStep): predicates are tracers, so the wrappers
  lower to `lax.while_loop` / `lax.cond` / `lax.switch` — compiled
  control flow with no host round-trips.  `lax.while_loop` is
  forward-only under reverse autodiff (XLA's constraint); `cond`/`switch`
  differentiate in both modes.  Loops that must be differentiated inside
  jit should carry a static bound (the lax.scan formulation the RNN layers
  use).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap

__all__ = ["while_loop", "cond", "case", "switch_case",
           "create_array", "array_write", "array_read",
           "array_length"]


def _is_traced(*vals):
    return any(isinstance(unwrap(v), jax.core.Tracer) for v in vals
               if v is not None)


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: Tensor(x) if not isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda x: unwrap(x) if isinstance(x, Tensor) else x, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop(cond, body, loop_vars).

    cond: callable(*loop_vars) -> scalar bool Tensor; body:
    callable(*loop_vars) -> same-structure list.  Returns the final
    loop_vars.  Eager: Python loop (tape-differentiable, dynamic trip
    count).  Traced: lax.while_loop (compiled, forward-only)."""
    if not callable(cond) or not callable(body):
        raise TypeError("cond and body must be callable")
    if not isinstance(loop_vars, (list, tuple)) or not loop_vars:
        raise ValueError("loop_vars must be a non-empty list/tuple")
    loop_vars = list(loop_vars)

    first = cond(*loop_vars)
    if jnp.shape(unwrap(first)) not in ((), (1,)):
        raise ValueError("cond must return a scalar boolean")

    if not _is_traced(first, *loop_vars):
        # eager: run the loop on the host; every body op hits the tape
        vars_ = loop_vars
        ok = bool(jnp.reshape(unwrap(first), ()))
        while ok:
            out = body(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
            if len(vars_) != len(loop_vars):
                raise ValueError("body must return as many values as "
                                 "loop_vars")
            ok = bool(jnp.reshape(unwrap(cond(*vars_)), ()))
        return vars_

    def cond_fn(carry):
        return jnp.reshape(unwrap(cond(*_wrap_tree(list(carry)))), ())

    def body_fn(carry):
        out = body(*_wrap_tree(list(carry)))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap_tree(out))

    final = jax.lax.while_loop(cond_fn, body_fn,
                               tuple(_unwrap_tree(loop_vars)))
    return _wrap_tree(list(final))


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """paddle.static.nn.cond(pred, true_fn, false_fn): runs true_fn() when
    pred else false_fn(); both must return matching structures.
    Differentiable in both eager (tape) and traced (lax.cond) modes."""
    pv = unwrap(pred)
    if true_fn is None and false_fn is None:
        raise ValueError("at least one of true_fn/false_fn is required")
    tf = true_fn if true_fn is not None else (lambda: None)
    ff = false_fn if false_fn is not None else (lambda: None)

    if not _is_traced(pred):
        return tf() if bool(jnp.reshape(pv, ())) else ff()

    out = jax.lax.cond(jnp.reshape(pv, ()).astype(bool),
                       lambda: _unwrap_tree(tf()),
                       lambda: _unwrap_tree(ff()))
    return _wrap_tree(out)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case: first pair whose pred is True wins; falls
    back to `default` (or the LAST pair's fn when default is None, like the
    reference)."""
    if not pred_fn_pairs:
        raise ValueError("pred_fn_pairs must be non-empty")
    for p, fn in pred_fn_pairs:
        if not callable(fn):
            raise TypeError("each pair must be (pred, callable)")
    preds = [p for p, _ in pred_fn_pairs]

    if not _is_traced(*preds):
        for p, fn in pred_fn_pairs:
            if bool(jnp.reshape(unwrap(p), ())):
                return fn()
        return default() if default is not None else pred_fn_pairs[-1][1]()

    # traced: nest lax.cond — first true pred shadows the rest
    fallback = default if default is not None else pred_fn_pairs[-1][1]

    def build(i):
        if i == len(pred_fn_pairs):
            return lambda: fallback()
        p, fn = pred_fn_pairs[i]
        rest = build(i + 1)
        return lambda: _wrap_tree(jax.lax.cond(
            jnp.reshape(unwrap(p), ()).astype(bool),
            lambda: _unwrap_tree(fn()), lambda: _unwrap_tree(rest())))

    return build(0)()


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case(branch_index, branch_fns, default).

    branch_fns: dict {int: callable} or list of (int, callable) / plain
    callables.  Unmatched index runs `default` (reference semantics).
    Traced mode lowers to ONE lax.switch (compiled jump table)."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    else:
        pairs = [(i, f) if callable(f) else tuple(f)
                 for i, f in enumerate(branch_fns)]
        if any(not callable(f) for _, f in pairs):
            raise TypeError("branch_fns entries must be callable")
        pairs = sorted(pairs)  # None-default = MAX-index branch (reference)
    keys = [int(k) for k, _ in pairs]
    fns = [f for _, f in pairs]
    if default is None:
        default = fns[-1]

    iv = unwrap(branch_index)
    if not _is_traced(branch_index):
        i = int(jnp.reshape(iv, ()))
        return dict(zip(keys, fns)).get(i, default)()

    # traced: map sparse keys onto a dense lax.switch table + default slot
    table = [lambda f=f: _unwrap_tree(f()) for f in fns]
    table.append(lambda: _unwrap_tree(default()))
    key_arr = jnp.asarray(keys, jnp.int32)
    idx = jnp.reshape(iv, ()).astype(jnp.int32)
    match = key_arr == idx
    dense = jnp.where(jnp.any(match), jnp.argmax(match), len(fns))
    return _wrap_tree(jax.lax.switch(dense, table))


# ---------------------------------------------------------------------------
# TensorArray verbs (reference: fluid/layers/control_flow.py —
# array_write:1455, array_read:1894, array_length:2023, create_array:1552).
# TPU-native: LoDTensorArray is a plain Python list (compat.py:124); these
# verbs give era-typical code its spelling.  In eager/StaticRNN use the
# index may be a Tensor or int; inside lax loops use lax.scan-carried
# dense buffers instead (the repo's jit answer to dynamic arrays).


def create_array(dtype="float32", initialized_list=None):
    from ..compat import LoDTensorArray
    arr = LoDTensorArray()
    if initialized_list:
        arr.extend(initialized_list)
    return arr


def _arr_index(i):
    from ..core.tensor import Tensor
    if isinstance(i, Tensor):
        return int(i.numpy().reshape(()))
    return int(i)


def array_write(x, i, array=None):
    if array is None:
        array = create_array()
    idx = _arr_index(i)
    if idx > len(array):
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"array_write: index {idx} would leave unwritten slots "
            f"(array length {len(array)}); the reference requires "
            f"i <= len(array)")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    return array[_arr_index(i)]


def array_length(array):
    from ..core.tensor import Tensor
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(array), jnp.int64), stop_gradient=True)


# ---------------------------------------------------------------------------
# static-graph layer builders (reference static/nn/__init__.py __all__):
# era spellings over the 2.0 homes.  LAZY delegation — fluid.layers imports
# THIS module at load (while_loop/cond/TensorArray verbs), so importing it
# back eagerly would cycle.


def _lazy(module_path, name):
    def f(*args, **kwargs):
        import importlib
        mod = importlib.import_module(module_path, __package__)
        return getattr(mod, name)(*args, **kwargs)
    f.__name__ = name
    f.__qualname__ = name
    f.__doc__ = f"static.nn.{name}: era alias of {module_path}.{name}"
    return f


fc = _lazy("..fluid.layers", "fc")
embedding = _lazy("..fluid.layers", "embedding")
bilinear_tensor_product = _lazy("..fluid.layers", "bilinear_tensor_product")
crf_decoding = _lazy("..fluid.layers", "crf_decoding")
data_norm = _lazy("..fluid.layers", "data_norm")
multi_box_head = _lazy("..fluid.layers", "multi_box_head")
nce = _lazy("..fluid.layers", "nce")
row_conv = _lazy("..fluid.layers", "row_conv")
spectral_norm = _lazy("..fluid.layers", "spectral_norm")
py_func = _lazy("..fluid.layers", "py_func")
group_norm = _lazy("..nn.functional", "group_norm")
instance_norm = _lazy("..nn.functional", "instance_norm")
layer_norm = _lazy("..nn.functional", "layer_norm")
prelu = _lazy("..nn.functional", "prelu")


def _run_conv(fname, input, weight, bias, act, kw):  # noqa: A002
    if weight is None:
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"static.nn.{fname}: pass `weight` (and optional `bias`) "
            f"explicitly — there is no LayerHelper parameter store; "
            f"or use the stateful nn.Conv layer family")
    import importlib
    F = importlib.import_module("..nn.functional", __package__)
    out = getattr(F, fname)(input, weight, bias, **kw)
    if act is not None:
        out = getattr(F, act)(out)
    return out


def _conv_builder(fname):
    """Era static-graph conv builders (reference static/nn: conv2d(input,
    num_filters, filter_size, stride, padding, ...) creates its weight via
    LayerHelper).  No program-scope parameter store here, so the era
    signature is accepted but the weight must be passed explicitly (the
    repo's documented convention for LayerHelper-created parameters — see
    fluid.layers.multi_box_head) or use the stateful nn.Conv*D layer."""
    def f(input, num_filters, filter_size, stride=1, padding=0,  # noqa: A002
          dilation=1, groups=1, param_attr=None, bias_attr=None,
          use_cudnn=True, act=None, name=None, data_format="NCHW",
          weight=None, bias=None):
        return _run_conv(fname, input, weight, bias, act,
                         dict(stride=stride, padding=padding,
                              dilation=dilation, groups=groups,
                              data_format=data_format))
    f.__name__ = fname
    f.__doc__ = _conv_builder.__doc__
    return f


def _conv_transpose_builder(fname):
    """Era transpose signature puts output_size BEFORE filter_size and
    padding before stride (reference fluid/layers/nn.py:3736
    conv2d_transpose(input, num_filters, output_size=None,
    filter_size=None, padding=0, stride=1, ...)) — positional era calls
    must bind correctly."""
    def f(input, num_filters, output_size=None, filter_size=None,  # noqa: A002
          padding=0, stride=1, dilation=1, groups=1, param_attr=None,
          bias_attr=None, use_cudnn=True, act=None, name=None,
          data_format="NCHW", weight=None, bias=None):
        kw = dict(stride=stride, padding=padding, dilation=dilation,
                  groups=groups, data_format=data_format)
        if output_size is not None:
            kw["output_size"] = output_size
        return _run_conv(fname, input, weight, bias, act, kw)
    f.__name__ = fname
    f.__doc__ = _conv_transpose_builder.__doc__
    return f


conv2d = _conv_builder("conv2d")
conv3d = _conv_builder("conv3d")
conv2d_transpose = _conv_transpose_builder("conv2d_transpose")
conv3d_transpose = _conv_transpose_builder("conv3d_transpose")


def batch_norm(input, act=None, is_test=False, momentum=0.9,  # noqa: A002
               epsilon=1e-5, param_attr=None, bias_attr=None,
               data_layout="NCHW", in_place=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False,
               weight=None, bias=None, running_mean=None, running_var=None):
    """Era static.nn.batch_norm: creates scale/shift/moving stats via
    LayerHelper in the reference.  Here the four state tensors must be
    passed explicitly (or use the stateful nn.BatchNorm layer, which owns
    them)."""
    if running_mean is None or running_var is None:
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            "static.nn.batch_norm: pass running_mean/running_var (and "
            "optional weight/bias) explicitly — there is no LayerHelper "
            "parameter store; or use the stateful nn.BatchNorm layer")
    import importlib
    F = importlib.import_module("..nn.functional", __package__)
    out = F.batch_norm(input, running_mean, running_var, weight, bias,
                       training=not (is_test or use_global_stats),
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act is not None:
        out = getattr(F, act)(out)
    return out
deform_conv2d = _lazy("..vision.ops", "deform_conv2d")
create_parameter = _lazy("..tensor.creation", "create_parameter")
