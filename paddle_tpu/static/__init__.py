"""paddle.static compatibility layer.

The reference's static-graph world (ProgramDesc + Executor) collapses into
jit tracing here (SURVEY.md §7); this module keeps the paddle.static names
usable: InputSpec for export signatures, save/load_inference_model over
jax.export artifacts.
"""
from ..jit import InputSpec, save as save_inference_model_jit, load as load_inference_model  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kw):
    raise NotImplementedError(
        "Use paddle_tpu.jit.save(layer, path, input_spec=[...]) — tracing "
        "replaces Program construction on TPU")


from . import nn  # noqa: F401,E402
