"""paddle.static compatibility layer.

The reference's static-graph world (ProgramDesc + Executor) collapses into
jit tracing here (SURVEY.md §7); this module keeps the paddle.static names
usable: InputSpec for export signatures, save/load_inference_model over
jax.export artifacts.
"""
from ..jit import InputSpec, save as save_inference_model_jit, load as load_inference_model  # noqa: F401


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, **kw):
    raise NotImplementedError(
        "Use paddle_tpu.jit.save(layer, path, input_spec=[...]) — tracing "
        "replaces Program construction on TPU")


from . import nn  # noqa: F401,E402


# ---------------------------------------------------------------------------
# Static-graph compat shims (reference: python/paddle/static/__init__.py
# re-exports the fluid Program/Executor machinery).  Tracing subsumes the
# Program world here (SURVEY §7): these shims keep ported code importable
# and give the legacy verbs their closest 2.0-native meaning — Executor.run
# fetches already-computed eager tensors, append_backward/gradients call the
# tape, scopes are dicts.  They are NOT a second execution engine.

import contextlib as _ctx

import numpy as _np


class Program:
    """Placeholder program object (identity-only: tracing is the capture)."""

    def __init__(self):
        self._state = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    # block-protocol stubs so introspection code doesn't crash
    @property
    def ops(self):
        return []

    def all_parameters(self):
        return []


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@_ctx.contextmanager
def program_guard(main_program, startup_program=None):
    """no-op guard: eager/traced execution has no ambient Program."""
    yield


@_ctx.contextmanager
def name_scope(prefix=None):
    yield


class Scope(dict):
    """Name -> value scope (reference framework::Scope)."""

    def var(self, name):
        return self.setdefault(name, None)

    def find_var(self, name):
        return self.get(name)


_global_scope = Scope()


def global_scope():
    return _global_scope


@_ctx.contextmanager
def scope_guard(scope):
    yield scope


def cpu_places(device_count=None):
    import jax
    devs = [d for d in jax.devices() if d.platform == "cpu"] or jax.devices()
    return devs[:device_count] if device_count else devs


def cuda_places(device_ids=None):
    import jax
    devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
    if device_ids:
        devs = [devs[i] for i in device_ids if i < len(devs)]
    return devs


class BuildStrategy:
    """Config holder (XLA owns fusion/memory passes — fields are inert)."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.memory_optimize = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Identity wrapper: jit compilation happens at trace time."""

    def __init__(self, program, build_strategy=None):
        self._program = program

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class Executor:
    """Legacy Executor verbs over the eager world: run() evaluates/fetches
    tensors that the (dygraph-executed) model code already produced."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kw):
        from ..core.tensor import Tensor, unwrap
        outs = []
        for f in (fetch_list or []):
            if isinstance(f, Tensor):
                outs.append(_np.asarray(unwrap(f)) if return_numpy else f)
            elif callable(f):
                r = f(**(feed or {}))
                outs.append(_np.asarray(unwrap(r)) if return_numpy else r)
            else:
                raise TypeError(
                    "Executor.run fetch_list entries must be Tensors (or "
                    "callables) in the tracing world — Programs hold no "
                    "graph to execute; see paddle_tpu.jit.to_static")
        return outs

    def close(self):
        pass


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Tape-backed: runs loss.backward() and returns (param, grad) pairs.
    With parameter_list=None (the dominant fluid pattern) the pairs cover
    every requires-grad leaf reachable from `loss`, like the reference."""
    if parameter_list is not None:
        params = list(parameter_list)
    else:  # discover leaves from the tape BEFORE backward frees it
        from ..core import tape as _tape
        params, seen = [], set()
        if loss._node is not None:
            for node in _tape._topo_order([loss._node]):
                for t in node.inputs:
                    if (t._node is None and not t.stop_gradient
                            and id(t) not in seen):
                        seen.add(id(t))
                        params.append(t)
    loss.backward()
    return [(p, p.grad) for p in params if getattr(p, "grad", None)
            is not None]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad as _grad
    outs = _grad(targets, inputs, grad_outputs=target_gradients)
    return outs


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """Eager: a python function IS an op."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    return func(*xs)


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: A002,N802
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    msg = message or ""
    print(f"{msg} shape={tuple(input.shape)} "
          f"values={_np.asarray(input.numpy()).reshape(-1)[:summarize]}")
    return input


class WeightNormParamAttr:
    """Accepted for compat; weight normalization is applied via
    nn.utils-style reparameterization in the 2.0 world, not a Program
    pass.  Falls back to a plain ParamAttr."""

    def __new__(cls, dim=None, **kwargs):
        from ..nn.layer_base import ParamAttr
        return ParamAttr(**kwargs)


def load_program_state(model_path, var_list=None):
    from ..framework import load as _load
    state = _load(model_path + ".pdparams" if not
                  model_path.endswith(".pdparams") else model_path,
                  return_numpy=True)
    return state


def set_program_state(program, state_dict):
    program._state = dict(state_dict)


# legacy aliases: ParallelExecutor collapses into CompiledProgram; the
# closest live object to a Variable is the Tensor itself
ParallelExecutor = CompiledProgram
from ..core.tensor import Tensor as Variable  # noqa: E402

# the fluid graph-builder verbs era code reaches via paddle.static.*
# (reference python/paddle/static/__init__.py re-exports)
from ..compat import data, create_global_var  # noqa: E402,F401
from ..tensor.creation import create_parameter  # noqa: E402,F401
from ..framework import save, load  # noqa: E402,F401
