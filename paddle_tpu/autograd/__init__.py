"""paddle_tpu.autograd (reference: python/paddle/autograd/ — PyLayer, backward)."""
from __future__ import annotations

from ..core.tensor import no_grad, enable_grad, is_grad_enabled  # noqa: F401
from ..core.tape import backward, grad  # noqa: F401
from ..core.op import dispatch
from ..core.tensor import Tensor, TapeNode


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom op with user-defined forward/backward
    (reference: python/paddle/autograd/py_layer.py)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        from ..core.tensor import is_grad_enabled
        tensors_in = [a for a in args if isinstance(a, Tensor)]
        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensors_in)

        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(out, (tuple, list))
        outs = [out] if single else list(out)

        if need_grad:
            diff_inputs = [t for t in tensors_in if not t.stop_gradient]

            def vjp_fn(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                ct_tensors = [Tensor(c) for c in cts]
                with no_grad():
                    gin = cls.backward(ctx, *ct_tensors)
                gin = [gin] if isinstance(gin, Tensor) or gin is None else list(gin)
                raws = []
                gi = iter(gin)
                for t in diff_inputs:
                    g = next(gi, None)
                    raws.append(None if g is None else
                                (g._data if isinstance(g, Tensor) else g))
                return raws

            new_outs = [Tensor(o._data if isinstance(o, Tensor) else o,
                               stop_gradient=False) for o in outs]
            node = TapeNode(cls.__name__, vjp_fn, diff_inputs, new_outs)
            for i, t in enumerate(new_outs):
                t._node = node
                t._out_index = i
            outs = new_outs
        return outs[0] if single else tuple(outs)
