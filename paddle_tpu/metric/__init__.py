"""Metrics (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc; operators/metrics/)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, unwrap


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = np.asarray(unwrap(pred))
        label_np = np.asarray(unwrap(label))
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        correct = (idx == label_np[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(unwrap(correct))
        num = c.shape[0]
        accs = []
        for k in self.topk:
            corr_k = c[..., :k].sum()
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
            accs.append(corr_k / max(num, 1))
        return np.asarray(accs[0] if len(accs) == 1 else accs)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).reshape(-1)
        l = np.asarray(unwrap(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).reshape(-1)
        l = np.asarray(unwrap(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC via thresholded confusion histogram
    (reference: operators/metrics/auc_op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        l = np.asarray(unwrap(labels)).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        else:
            p = p.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over descending threshold
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional accuracy (reference: layers/metric_op.py accuracy)."""
    import jax.numpy as jnp
    from ..core.op import dispatch

    def raw(x, l):
        topk_idx = jnp.argsort(-x, axis=-1)[..., :k]
        lbl = l if l.ndim == 1 else l[..., 0]
        corr = jnp.any(topk_idx == lbl[..., None], axis=-1)
        return jnp.mean(corr.astype(jnp.float32))
    return dispatch("accuracy", raw, input, label)


# one accumulator per (curve, num_thresholds): the reference's
# fluid.layers.auc binds persistent stat variables to the single auc op in
# the program, accumulated across exe.run calls — the eager analogue is a
# module-level stream per config (use metric.Auc for independent streams)
_AUC_STREAMS = {}


def auc(input, label, curve="ROC", num_thresholds=2 ** 12 - 1,  # noqa: A002
        topk=1, slide_steps=1, name=None):
    """Functional streaming AUC (reference: layers/metric_op.py:111 over
    operators/metrics/auc_op).  Returns (accumulated auc, batch auc,
    [batch_stat_pos, batch_stat_neg, stat_pos, stat_neg]) like the
    reference's (auc_out, batch_auc_out, state list).

    Deviations from the reference, stated rather than silent: the batch
    statistic is the exact CURRENT batch (not a slide_steps window), and a
    new eval stream needs `metric.auc.reset()` (the reference binds fresh
    stat variables per program; use `metric.Auc` for independent
    streams)."""
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.errors import UnimplementedError
    if topk != 1:
        raise UnimplementedError(
            "auc: topk != 1 is not supported (the reference only consumes "
            "the positive-class column as well — pass 2-column preds)")
    key = (curve, int(num_thresholds))
    stream = _AUC_STREAMS.get(key)
    if stream is None:
        stream = _AUC_STREAMS[key] = Auc(curve=curve,
                                         num_thresholds=num_thresholds)
    batch = Auc(curve=curve, num_thresholds=num_thresholds)
    batch.update(input, label)
    stream._stat_pos += batch._stat_pos
    stream._stat_neg += batch._stat_neg
    stats = [batch._stat_pos, batch._stat_neg,
             stream._stat_pos.copy(), stream._stat_neg.copy()]
    return (Tensor(jnp.asarray(stream.accumulate(), jnp.float32),
                   stop_gradient=True),
            Tensor(jnp.asarray(batch.accumulate(), jnp.float32),
                   stop_gradient=True),
            [Tensor(jnp.asarray(s), stop_gradient=True) for s in stats])


def _auc_reset():
    """Clear all functional-auc accumulation streams (fresh eval run)."""
    _AUC_STREAMS.clear()


auc.reset = _auc_reset


# ---------------------------------------------------------------------------
# functional metric ops (reference: python/paddle/metric/metrics.py exposes
# accuracy + the fluid ops mean_iou / chunk_eval)


def mean_iou(input, label, num_classes, name=None):  # noqa: A002
    """Mean Intersection-over-Union for segmentation (reference:
    operators/mean_iou_op).  Returns (mean_iou scalar, out_wrong (C,),
    out_correct (C,))."""
    import jax.numpy as jnp
    from ..core.op import dispatch

    def raw(pred, lab):
        p = pred.reshape(-1).astype(jnp.int32)
        l = lab.reshape(-1).astype(jnp.int32)  # noqa: E741
        valid = (l >= 0) & (l < num_classes)
        correct_mask = valid & (p == l)
        correct = jnp.zeros((num_classes,), jnp.int32).at[
            jnp.where(correct_mask, l, num_classes)].add(1, mode="drop")
        pred_cnt = jnp.zeros((num_classes,), jnp.int32).at[
            jnp.where(valid, p, num_classes)].add(1, mode="drop")
        lab_cnt = jnp.zeros((num_classes,), jnp.int32).at[
            jnp.where(valid, l, num_classes)].add(1, mode="drop")
        union = pred_cnt + lab_cnt - correct
        present = union > 0
        iou = jnp.where(present, correct / jnp.maximum(union, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
        # reference out_wrong = union - correct, so streaming consumers can
        # rebuild iou = correct / (correct + wrong)
        wrong = union - correct
        return miou.astype(jnp.float32), wrong, correct
    return dispatch("mean_iou", raw, input, label)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None, name=None):
    """Chunk-level precision/recall/F1 for sequence labeling (reference:
    operators/chunk_eval_op, schemes IOB/IOE/IOBES/plain).

    input/label: (B, T) int tag ids laid out scheme-major (IOB: tag =
    chunk_type * 2 + {0: B, 1: I}, IOBES: * 4 + {B, I, E, S}; plain: tag =
    chunk_type; num_chunk_types * tags_per_scheme is the "outside" tag).
    Host-side eval metric (like multiclass_nms): returns (precision,
    recall, f1, num_infer_chunks, num_label_chunks, num_correct_chunks).
    """
    import jax
    pred = np.asarray(jax.device_get(unwrap(input)))
    lab = np.asarray(jax.device_get(unwrap(label)))
    if pred.ndim == 1:
        pred, lab = pred[None], lab[None]
    lens = (np.asarray(jax.device_get(unwrap(seq_length)))
            if seq_length is not None
            else np.full((pred.shape[0],), pred.shape[1]))
    excluded = set(excluded_chunk_types or ())

    tag_counts = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}
    if chunk_scheme not in tag_counts:
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(f"unknown chunk_scheme {chunk_scheme!r}")
    k = tag_counts[chunk_scheme]

    def chunks(seq):
        """Decode (start, end, type) chunks: one pass, explicit open/close
        rules per scheme (IOB pos 0=B 1=I; IOE 0=I 1=E; IOBES 0=B 1=I 2=E
        3=S; plain = maximal same-type runs)."""
        out = []
        start = ctype = None

        def close(end):
            nonlocal start, ctype
            if start is not None:
                out.append((start, end, ctype))
            start = ctype = None

        for i, t in enumerate(seq):
            t = int(t)
            if t >= num_chunk_types * k or t < 0:  # outside tag
                close(i - 1)
                continue
            ty, pos = divmod(t, k)
            if chunk_scheme == "plain":
                if ctype != ty or start is None:
                    close(i - 1)
                    start, ctype = i, ty
            elif chunk_scheme == "IOB":
                if pos == 0 or ctype != ty or start is None:
                    close(i - 1)
                    start, ctype = i, ty
            elif chunk_scheme == "IOE":
                if ctype != ty or start is None:
                    close(i - 1)
                    start, ctype = i, ty
                if pos == 1:  # E includes this position, then closes
                    close(i)
            else:  # IOBES
                if pos == 3:  # S: single-token chunk
                    close(i - 1)
                    out.append((i, i, ty))
                    continue
                if pos == 0 or ctype != ty or start is None:
                    close(i - 1)
                    start, ctype = i, ty
                if pos == 2:  # E closes including this position
                    close(i)
        close(len(seq) - 1)
        return {c for c in out if c[2] not in excluded}

    n_inf = n_lab = n_cor = 0
    for b in range(pred.shape[0]):
        L = int(lens[b])
        ic = chunks(pred[b, :L])
        lc = chunks(lab[b, :L])
        n_inf += len(ic)
        n_lab += len(lc)
        n_cor += len(ic & lc)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    import jax.numpy as jnp
    mk = lambda v, dt=jnp.float32: Tensor(jnp.asarray(v, dt))  # noqa: E731
    return (mk(prec), mk(rec), mk(f1), mk(n_inf, jnp.int64),
            mk(n_lab, jnp.int64), mk(n_cor, jnp.int64))


# reference module-name alias (paddle.metric.metrics)
import sys as _sys
metrics = _sys.modules[__name__]
