"""Metrics (reference: python/paddle/metric/metrics.py — Metric base,
Accuracy, Precision, Recall, Auc; operators/metrics/)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, unwrap


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = np.asarray(unwrap(pred))
        label_np = np.asarray(unwrap(label))
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        correct = (idx == label_np[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(unwrap(correct))
        num = c.shape[0]
        accs = []
        for k in self.topk:
            corr_k = c[..., :k].sum()
            self.total[self.topk.index(k)] += corr_k
            self.count[self.topk.index(k)] += num
            accs.append(corr_k / max(num, 1))
        return np.asarray(accs[0] if len(accs) == 1 else accs)

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).reshape(-1)
        l = np.asarray(unwrap(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fp += int(np.sum(pred_pos & (l == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds)).reshape(-1)
        l = np.asarray(unwrap(labels)).reshape(-1)
        pred_pos = p > 0.5
        self.tp += int(np.sum(pred_pos & (l == 1)))
        self.fn += int(np.sum(~pred_pos & (l == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Streaming AUC via thresholded confusion histogram
    (reference: operators/metrics/auc_op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        l = np.asarray(unwrap(labels)).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        else:
            p = p.reshape(-1)
        bins = np.minimum((p * self.num_thresholds).astype(np.int64),
                          self.num_thresholds - 1)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # integrate over descending threshold
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional accuracy (reference: layers/metric_op.py accuracy)."""
    import jax.numpy as jnp
    from ..core.op import dispatch

    def raw(x, l):
        topk_idx = jnp.argsort(-x, axis=-1)[..., :k]
        lbl = l if l.ndim == 1 else l[..., 0]
        corr = jnp.any(topk_idx == lbl[..., None], axis=-1)
        return jnp.mean(corr.astype(jnp.float32))
    return dispatch("accuracy", raw, input, label)
