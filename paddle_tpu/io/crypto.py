"""Model-artifact encryption (AES-CTR, native).

Reference: framework/io/crypto/cipher.h (Cipher/CipherFactory),
aes_cipher.cc (cryptopp AES), pybind/crypto.cc (python surface).  Here the
block cipher is a self-contained C++ AES (native/src/crypto.cc) driven over
ctypes; CTR mode makes encrypt/decrypt one code path.  Wire format (v2):
  magic 'PDTC' | 1-byte version | 16-byte IV | ciphertext | 32-byte HMAC
The HMAC-SHA256 (keyed off a derived mac key) covers version|IV|ciphertext
and is verified BEFORE decryption — CTR is malleable and the payload often
feeds pickle, so tampering must fail closed.  v1 artifacts (no tag, parity
with the reference's unauthenticated cipher) load only behind an explicit
allow_legacy=True: accepting a v1 header by default would let an attacker
bypass the v2 HMAC by rewriting the version byte and stripping the tag.
"""
from __future__ import annotations

import ctypes
import hashlib
import hmac as _hmac
import os
from typing import Optional

import numpy as np

from ..native import load_module, NativeBuildError

__all__ = ["AESCipher", "CipherFactory", "CipherUtils"]

_MAGIC = b"PDTC"
_VERSION = 2
_TAG_LEN = 32


def _mac_key(key: bytes) -> bytes:
    # Domain-separate the MAC key from the cipher key.
    return hashlib.sha256(b"pdtpu-artifact-mac:" + key).digest()


def _lib():
    lib = load_module("crypto")
    if lib.pdtpu_aes_ctr_crypt.argtypes is None:
        lib.pdtpu_aes_ctr_crypt.restype = ctypes.c_int
        lib.pdtpu_aes_ctr_crypt.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong]
        lib.pdtpu_aes_encrypt_block.restype = ctypes.c_int
        lib.pdtpu_aes_encrypt_block.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint8)]
    return lib


def _ctr_crypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    buf = np.frombuffer(data, np.uint8).copy()
    if buf.size:
        rc = _lib().pdtpu_aes_ctr_crypt(
            key, len(key), iv,
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.size)
        if rc != 0:
            raise ValueError(f"bad AES key length {len(key)} "
                             "(expect 16/24/32 bytes)")
    return buf.tobytes()


def encrypt_block(key: bytes, block16: bytes) -> bytes:
    """Single-block AES encrypt — used by known-answer tests."""
    out = np.zeros(16, np.uint8)
    rc = _lib().pdtpu_aes_encrypt_block(
        key, len(key), block16,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise ValueError("bad AES key length")
    return out.tobytes()


class AESCipher:
    """AES-CTR cipher with the reference Cipher interface (cipher.h:24)."""

    def __init__(self, key_size: int = 16):
        if key_size not in (16, 24, 32):
            raise ValueError("key_size must be 16/24/32 bytes")
        self._key_size = key_size

    def _check_key(self, key: bytes):
        if len(key) not in (16, 24, 32):
            raise ValueError(f"AES key must be 16/24/32 bytes, got {len(key)}")

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        self._check_key(key)
        iv = os.urandom(16)
        body = bytes([_VERSION]) + iv + _ctr_crypt(key, iv, plaintext)
        tag = _hmac.new(_mac_key(key), body, hashlib.sha256).digest()
        return _MAGIC + body + tag

    def decrypt(self, ciphertext: bytes, key: bytes,
                allow_legacy: bool = False) -> bytes:
        """allow_legacy gates v1 (unauthenticated) artifacts: without it a
        v1 header is rejected, else rewriting the version byte and stripping
        the tag would silently bypass the v2 HMAC (CTR is malleable and the
        payload often feeds pickle)."""
        self._check_key(key)
        head = len(_MAGIC) + 1 + 16
        if (len(ciphertext) < head
                or ciphertext[:len(_MAGIC)] != _MAGIC):
            raise ValueError("not a paddle_tpu encrypted artifact")
        version = ciphertext[len(_MAGIC)]
        if version == 2:
            if len(ciphertext) < head + _TAG_LEN:
                raise ValueError("truncated encrypted artifact")
            body, tag = ciphertext[len(_MAGIC):-_TAG_LEN], \
                ciphertext[-_TAG_LEN:]
            want = _hmac.new(_mac_key(key), body, hashlib.sha256).digest()
            if not _hmac.compare_digest(tag, want):
                raise ValueError(
                    "encrypted artifact failed integrity check "
                    "(wrong key or tampered file)")
            iv = ciphertext[len(_MAGIC) + 1:head]
            return _ctr_crypt(key, iv, ciphertext[head:-_TAG_LEN])
        elif version == 1:  # legacy unauthenticated format
            if not allow_legacy:
                raise ValueError(
                    "refusing unauthenticated v1 encrypted artifact "
                    "(version-downgrade would bypass the v2 HMAC); pass "
                    "allow_legacy=True only for trusted legacy files")
            iv = ciphertext[len(_MAGIC) + 1:head]
            return _ctr_crypt(key, iv, ciphertext[head:])
        raise ValueError(f"unknown encrypted-artifact version {version}")

    def encrypt_to_file(self, plaintext: bytes, key: bytes, filename: str):
        d = os.path.dirname(filename)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(filename, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, filename: str,
                          allow_legacy: bool = False) -> bytes:
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key, allow_legacy=allow_legacy)


class CipherFactory:
    """CipherFactory.create_cipher (cipher.h:45).  The reference picks the
    implementation from a config file; only AES-CTR exists here."""

    @staticmethod
    def create_cipher(config_file: Optional[str] = None) -> AESCipher:
        key_size = 16
        if config_file and os.path.exists(config_file):
            with open(config_file) as f:
                for line in f:
                    k, _, v = line.partition(":")
                    if k.strip() == "cipher_key_size":
                        key_size = int(v.strip()) // 8
        return AESCipher(key_size=key_size)


class CipherUtils:
    """Key helpers (cipher_utils.h: GenKey/GenKeyToFile/ReadKeyFromFile)."""

    @staticmethod
    def gen_key(length_bits: int = 128) -> bytes:
        if length_bits not in (128, 192, 256):
            raise ValueError("key length must be 128/192/256 bits")
        return os.urandom(length_bits // 8)

    @staticmethod
    def gen_key_to_file(length_bits: int, filename: str) -> bytes:
        key = CipherUtils.gen_key(length_bits)
        d = os.path.dirname(filename)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(filename, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename: str) -> bytes:
        with open(filename, "rb") as f:
            return f.read()


def available() -> bool:
    try:
        _lib()
        return True
    except NativeBuildError:
        return False
