"""DataLoader worker-side code.

Kept in its own module with NO framework imports at module scope: under the
"forkserver"/"spawn" start methods the worker process imports this module to
unpickle its target, and it must not drag jax (or the whole paddle_tpu
package) into every worker — numpy is all the hot path needs.  The fork
start method shares this code too.

Reference: the worker half of python/paddle/fluid/reader.py:412
(_worker_loop + shared-memory tensor transfer).
"""
from __future__ import annotations

import numpy as np

SHM_MIN_BYTES = 1 << 14  # small arrays go through the pickle queue


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference: reader.py
    default_collate).  Framework Tensors are detected lazily so this module
    stays importable without jax."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if type(sample).__name__ == "Tensor" and hasattr(sample, "_data"):
        return np.stack([np.asarray(b._data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


def fetch(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


class ShmRef:
    __slots__ = ("name", "shape", "dtype")

    def __init__(self, name, shape, dtype):
        self.name = name
        self.shape = shape
        self.dtype = dtype


def encode(obj, use_shm):
    from multiprocessing import shared_memory
    if isinstance(obj, tuple):
        return tuple(encode(o, use_shm) for o in obj)
    if isinstance(obj, list):
        return [encode(o, use_shm) for o in obj]
    if isinstance(obj, dict):
        return {k: encode(v, use_shm) for k, v in obj.items()}
    if (use_shm and isinstance(obj, np.ndarray)
            and obj.nbytes >= SHM_MIN_BYTES):
        shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
        view = np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)
        view[...] = obj
        ref = ShmRef(shm.name, obj.shape, str(obj.dtype))
        shm.close()
        # ownership transfers to the consumer (which unlinks after copying);
        # drop this process's resource-tracker claim so its exit cleanup
        # doesn't race a block the parent already removed
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:
            pass
        return ref
    return obj


def decode(obj):
    from multiprocessing import shared_memory
    if isinstance(obj, tuple):
        return tuple(decode(o) for o in obj)
    if isinstance(obj, list):
        return [decode(o) for o in obj]
    if isinstance(obj, dict):
        return {k: decode(v) for k, v in obj.items()}
    if isinstance(obj, ShmRef):
        shm = shared_memory.SharedMemory(name=obj.name)
        try:
            view = np.ndarray(obj.shape, np.dtype(obj.dtype), buffer=shm.buf)
            out = np.array(view)  # own the data before releasing the block
        finally:
            shm.close()
            shm.unlink()
        return out
    return obj


_worker_info = None  # set inside worker processes (io.get_worker_info)


class WorkerInfo:
    """reference: paddle.io.get_worker_info — worker id / pool size /
    dataset handle (lives here so worker processes never import jax)."""

    def __init__(self, id, num_workers, dataset=None):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


def _maybe_crash(seq, raw):
    """Fault injection (mirror of utils.faults.maybe_crash_worker, parsed
    inline so worker processes never import the framework): `raw` is the
    PDTPU_FAULT_WORKER_CRASH config string, read by the PARENT at worker
    spawn time and passed as a worker arg — a forkserver's cached
    environment must not decide whether a fault is armed.
    "kill:S[:/path/once]" hard-exits this worker when it picks up batch seq
    S (mode "exc" raises instead); the optional `once` sentinel file limits
    the fault to a single firing so the respawned worker survives the
    retried batch."""
    import os
    if not raw:
        return
    parts = raw.split(":", 2)
    if parts[0] in ("kill", "exc"):
        mode, target = parts[0], int(parts[1])
        once = parts[2] if len(parts) == 3 else None
    else:
        mode, target, once = "kill", int(parts[0]), None
    if seq != target:
        return
    if once is not None:
        if os.path.exists(once):
            return
        open(once, "w").close()
    if mode == "exc":
        raise RuntimeError(f"injected worker exception at seq {seq}")
    os._exit(17)  # hard crash: no result, no cleanup — the real thing


def worker_loop(dataset, collate_fn, task_q, result_q, worker_id,
                use_shm, worker_init_fn, num_workers=0, crash_cfg=None):
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset)
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        item = task_q.get()
        if item is None:
            break
        epoch, seq, indices = item
        try:
            _maybe_crash(seq, crash_cfg)
            batch = encode(fetch(dataset, indices, collate_fn), use_shm)
            result_q.put((epoch, seq, batch, None))
        except Exception as e:  # surface worker errors to the parent
            result_q.put((epoch, seq, None, f"{type(e).__name__}: {e}"))
