"""paddle_tpu.io (reference: python/paddle/io/)."""
from .dataset import (Dataset, IterableDataset, TensorDataset,  # noqa: F401
                      ComposeDataset, ChainDataset, ConcatDataset, Subset,
                      random_split, Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .fs import (LocalFS, HDFSClient, get_fs, ExecuteError,  # noqa: F401
                 FSFileExistsError, FSFileNotExistsError, FSTimeOut)


from ._worker import WorkerInfo  # noqa: E402


def get_worker_info():
    """Inside a DataLoader worker process: (id, num_workers); None in the
    main process.  Map-style workers set this via io._worker."""
    from . import _worker
    return getattr(_worker, "_worker_info", None)
