"""DataLoader.

Reference: python/paddle/fluid/reader.py:412 (DataLoader: forked worker
processes + shared-memory tensor transfer + _DataLoaderIter reorder logic)
feeding operators/reader/buffered_reader.cc (device double-buffering).

TPU-native design:
- num_workers > 0 starts worker PROCESSES; each worker materializes+collates
  its index batch and ships the arrays through POSIX shared memory
  (multiprocessing.shared_memory), the analogue of the reference's mmap'd
  _shared_memory tensors.  Results are re-ordered by sequence number and the
  number of in-flight batches is bounded by num_workers * prefetch_factor —
  never the whole epoch.
- start method: "fork" matches the reference and is cheapest, but forking a
  process that already carries live XLA/jax runtime threads can deadlock
  the child on an inherited lock.  So when the parent is multi-threaded the
  pool defaults to "forkserver" (workers import only numpy + the user's
  dataset module — see io/_worker.py); `multiprocessing_context=` overrides.
- the consumer side stages batches onto the device asynchronously
  (jax.device_put pipeline) — the buffered_reader equivalent.
- persistent_workers keeps the pool alive across epochs; worker_init_fn
  runs once in each worker (reference semantics).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import pickle
import queue
import threading
import warnings
from typing import Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from .dataset import BatchSampler, IterableDataset
from ._worker import (default_collate_fn, fetch as _fetch,  # noqa: F401
                      decode as _decode, worker_loop as _worker_loop)


def _default_mp_context() -> str:
    """"fork" when single-threaded (cheap, reference behavior); "forkserver"
    once runtime threads exist — forking a jax/XLA-threaded parent can
    deadlock the child on an inherited lock."""
    if threading.active_count() > 1:
        return "forkserver"
    return "fork"


class _WorkerPool:
    """Worker processes with bounded in-flight tasks + reordering."""

    def __init__(self, dataset, collate_fn, num_workers, use_shm,
                 worker_init_fn, timeout, mp_context=None):
        if mp_context is None or isinstance(mp_context, str):
            method = mp_context or _default_mp_context()
        else:
            method = mp_context.get_start_method()
        self._timeout = timeout if timeout and timeout > 0 else None
        self._epoch = 0
        try:
            self._start(mp.get_context(method), dataset, collate_fn,
                        num_workers, use_shm, worker_init_fn)
        except (AttributeError, TypeError, pickle.PicklingError) as e:
            if method == "fork" or mp_context is not None:
                raise
            # forkserver/spawn needs picklable dataset/collate/init_fn;
            # locally-defined ones force the fork path (reference behavior,
            # at the cost of fork-with-threads deadlock risk)
            warnings.warn(
                f"DataLoader falling back to fork workers: {e} "
                "(make dataset/collate_fn/worker_init_fn module-level "
                "picklables to use the thread-safe forkserver start method)",
                RuntimeWarning)
            self._start(mp.get_context("fork"), dataset, collate_fn,
                        num_workers, use_shm, worker_init_fn)

    def _start(self, ctx, dataset, collate_fn, num_workers, use_shm,
               worker_init_fn):
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._procs = [
            ctx.Process(target=_worker_loop,
                        args=(dataset, collate_fn, self._task_q,
                              self._result_q, wid, use_shm, worker_init_fn,
                              num_workers),
                        daemon=True)
            for wid in range(num_workers)]
        try:
            for p in self._procs:
                p.start()
        except Exception:
            for p in self._procs:
                if p.is_alive():
                    p.terminate()
            self._procs = []
            raise

    def _get_result(self):
        """Blocking result fetch that detects dead workers and honors the
        user timeout with a meaningful error (reference: reader.py raises on
        worker exit; torch detects OOM-killed workers the same way)."""
        waited = 0.0
        while True:
            try:
                return self._result_q.get(timeout=1.0)
            except queue.Empty:
                if not self.alive():
                    from ..core.errors import UnavailableError
                    raise UnavailableError(
                        "[Unavailable] DataLoader worker process died "
                        "unexpectedly (killed or crashed) with a task in "
                        "flight")
                waited += 1.0
                if self._timeout is not None and waited >= self._timeout:
                    from ..core.errors import ExecutionTimeoutError
                    raise ExecutionTimeoutError(
                        f"[ExecutionTimeout] DataLoader worker timed out "
                        f"after {waited:.0f}s")

    def run(self, index_batches, max_in_flight):
        """Yield collated numpy batches in order.

        Every task/result carries an epoch id: stale in-flight results from
        an abandoned or failed earlier run (persistent workers) are decoded
        and dropped — decoding frees their shared-memory blocks and keeps
        sequence numbers from colliding across epochs."""
        self._epoch += 1
        epoch = self._epoch
        it = enumerate(index_batches)
        pending = {}
        next_seq = 0
        in_flight = 0
        exhausted = False
        try:
            while True:
                while not exhausted and in_flight < max_in_flight:
                    try:
                        seq, idx = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    self._task_q.put((epoch, seq, list(idx)))
                    in_flight += 1
                if in_flight == 0:
                    return
                while next_seq not in pending:
                    ep, seq, batch, err = self._get_result()
                    if ep != epoch:
                        if batch is not None:
                            _decode(batch)  # free stale shm, discard
                        continue
                    if err is not None:
                        raise RuntimeError(
                            f"DataLoader worker failed: {err}")
                    pending[seq] = batch
                in_flight -= 1
                yield _decode(pending.pop(next_seq))
                next_seq += 1
        finally:
            # abandoned / failed epoch: free every shm block we can see now;
            # later-arriving strays are freed by the stale-epoch branch above
            # on the next run, or by shutdown()'s drain
            for b in pending.values():
                _decode(b)
            self._drain()

    def _drain(self):
        """Decode-and-discard everything currently in the result queue
        (frees shared-memory blocks whose ownership passed to this side)."""
        while True:
            try:
                _, _, batch, _ = self._result_q.get_nowait()
            except queue.Empty:
                return
            except Exception:
                return
            if batch is not None:
                _decode(batch)

    def shutdown(self):
        import time as _time
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except Exception:
                pass
        # drain WHILE joining: a worker blocked on a full result pipe can
        # only reach its exit sentinel if this side keeps consuming (and
        # decoding frees the shm ownership that was transferred to us)
        deadline = _time.monotonic() + 5.0
        procs = list(self._procs)
        while procs and _time.monotonic() < deadline:
            self._drain()
            procs = [p for p in procs if p.is_alive()]
            if procs:
                procs[0].join(timeout=0.1)
        for p in procs:
            p.terminate()
        self._procs = []
        self._drain()  # workers have exited: anything left is ours to free

    def alive(self):
        return bool(self._procs) and all(p.is_alive() for p in self._procs)


class DataLoader:
    """paddle.io.DataLoader — iterates device-resident batches."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, multiprocessing_context=None):
        from ..core.errors import InvalidArgumentError
        if batch_sampler is None and (not isinstance(batch_size, int)
                                      or batch_size <= 0):
            raise InvalidArgumentError(
                f"[DataLoader] batch_size must be a positive int, got "
                f"{batch_size!r}")
        if not isinstance(num_workers, int) or num_workers < 0:
            raise InvalidArgumentError(
                f"[DataLoader] num_workers must be a non-negative int, got "
                f"{num_workers!r}")
        if timeout and timeout < 0:
            raise InvalidArgumentError(
                f"[DataLoader] timeout must be >= 0, got {timeout!r}")
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.prefetch = self.prefetch_factor if use_buffer_reader else 0
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.multiprocessing_context = multiprocessing_context
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            if num_workers:
                import warnings
                warnings.warn(
                    "DataLoader(num_workers>0) over an IterableDataset "
                    "runs single-process: parallel workers would need "
                    "stream sharding the dataset does not declare "
                    "(map-style datasets DO use the worker pool)",
                    stacklevel=2)
                self.num_workers = 0
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        self._pool: Optional[_WorkerPool] = None
        self._pool_busy = False
        self._pool_lock = threading.Lock()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def __del__(self):
        try:
            if self._pool is not None:
                self._pool.shutdown()
        except Exception:
            pass

    def _new_pool(self):
        return _WorkerPool(self.dataset, self.collate_fn, self.num_workers,
                           self.use_shared_memory, self.worker_init_fn,
                           self.timeout,
                           mp_context=self.multiprocessing_context)

    def _acquire_pool(self):
        """Returns (pool, owned): owned pools are shut down by the caller.
        Persistent workers are reused across epochs, but a concurrent second
        iterator over the same loader gets its own temporary pool (the shared
        result queue cannot serve two epochs at once).  The check-and-mark is
        under a lock: two threads iterating one loader must not both claim
        the persistent pool."""
        if not self.persistent_workers:
            return self._new_pool(), True
        with self._pool_lock:
            if self._pool is not None and (self._pool_busy
                                           or not self._pool.alive()):
                if not self._pool_busy:
                    self._pool.shutdown()
                    self._pool = None
                else:  # concurrent iteration: temporary private pool
                    return self._new_pool(), True
            if self._pool is None:
                self._pool = self._new_pool()
            self._pool_busy = True
            return self._pool, False

    def _batches_numpy(self):
        if self._iterable_mode:
            # workers for iterable datasets would need stream sharding;
            # single-process here (the common map-style path is parallel)
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers > 0:
            pool, owned = self._acquire_pool()
            max_in_flight = self.num_workers * self.prefetch_factor
            try:
                yield from pool.run(self.batch_sampler, max_in_flight)
            finally:
                if owned:
                    pool.shutdown()
                else:
                    with self._pool_lock:
                        self._pool_busy = False
        else:
            for idx in self.batch_sampler:
                yield _fetch(self.dataset, idx, self.collate_fn)

    def __iter__(self):
        # device prefetch pipeline (buffered_reader equivalent): stage the
        # next `prefetch` batches onto the device asynchronously.
        from ..utils.monitor import stat_add

        def to_device(np_batch):
            stat_add("STAT_dataloader_batch_count")
            stat_add("STAT_dataloader_bytes",
                     sum(a.nbytes for a in jax.tree_util.tree_leaves(np_batch)
                         if isinstance(a, np.ndarray)))
            return jax.tree_util.tree_map(
                lambda a: Tensor(jax.device_put(a)) if isinstance(a, np.ndarray) else a,
                np_batch)

        if self.prefetch <= 0:
            for b in self._batches_numpy():
                yield to_device(b)
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()

        def put_bounded(item):
            # blocking put that aborts if the consumer has gone away
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def producer():
            gen = self._batches_numpy()
            try:
                for b in gen:
                    put_bounded(to_device(b))  # device_put is async
                    if stop.is_set():
                        break
            except BaseException as e:  # re-raised on the consumer side
                put_bounded(e)
            finally:
                gen.close()  # runs _batches_numpy's pool cleanup
                put_bounded(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()  # consumer broke early: unblock + clean up producer
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=10)
