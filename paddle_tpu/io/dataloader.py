"""DataLoader.

Reference: python/paddle/fluid/reader.py DataLoader (multiprocess workers +
shared-mem mmap tensors) feeding operators/reader/buffered_reader.cc (device
double-buffering).  TPU-native: multiprocess loading via a process pool +
host->device prefetch pipeline (async device_put of the next batches while the
current one computes) — the buffered_reader equivalent.
"""
from __future__ import annotations

import itertools
import queue
import threading
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor

import jax
import numpy as np

from ..core.tensor import Tensor
from .dataset import BatchSampler, IterableDataset


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference: reader.py default_collate)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn([b[i] for b in batch])
                     for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b._data) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    return batch


def _fetch(dataset, indices, collate_fn):
    return collate_fn([dataset[i] for i in indices])


class DataLoader:
    """paddle.io.DataLoader — iterates device-resident batches."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor) if use_buffer_reader else 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        self._pool = None

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def _batches_numpy(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers > 0:
            # thread pool: dataset __getitem__ is typically numpy/PIL — the
            # GIL is released in those C extensions; processes would require
            # picklable datasets (we keep the reference's worker semantics
            # without its shared-memory machinery).
            with ThreadPoolExecutor(self.num_workers) as pool:
                futures = [pool.submit(_fetch, self.dataset, idx, self.collate_fn)
                           for idx in self.batch_sampler]
                for fut in futures:
                    yield fut.result()
        else:
            for idx in self.batch_sampler:
                yield _fetch(self.dataset, idx, self.collate_fn)

    def __iter__(self):
        # device prefetch pipeline (buffered_reader equivalent): stage the
        # next `prefetch` batches onto the device asynchronously.
        def to_device(np_batch):
            return jax.tree_util.tree_map(
                lambda a: Tensor(jax.device_put(a)) if isinstance(a, np.ndarray) else a,
                np_batch)

        if self.prefetch <= 0:
            for b in self._batches_numpy():
                yield to_device(b)
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()

        def producer():
            try:
                for b in self._batches_numpy():
                    q.put(to_device(b))  # device_put is async; enqueue ahead
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        t.join()
