"""DataLoader.

Reference: python/paddle/fluid/reader.py:412 (DataLoader: forked worker
processes + shared-memory tensor transfer + _DataLoaderIter reorder logic)
feeding operators/reader/buffered_reader.cc (device double-buffering).

TPU-native design:
- num_workers > 0 starts worker PROCESSES; each worker materializes+collates
  its index batch and ships the arrays through POSIX shared memory
  (multiprocessing.shared_memory), the analogue of the reference's mmap'd
  _shared_memory tensors.  Results are re-ordered by sequence number and the
  number of in-flight batches is bounded by num_workers * prefetch_factor —
  never the whole epoch.
- start method: "fork" matches the reference and is cheapest, but forking a
  process that already carries live XLA/jax runtime threads can deadlock
  the child on an inherited lock.  So when the parent is multi-threaded the
  pool defaults to "forkserver" (workers import only numpy + the user's
  dataset module — see io/_worker.py); `multiprocessing_context=` overrides.
- the consumer side stages batches onto the device asynchronously
  (jax.device_put pipeline) — the buffered_reader equivalent.
- persistent_workers keeps the pool alive across epochs; worker_init_fn
  runs once in each worker (reference semantics).
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import pickle
import queue
import threading
import time
import warnings
from typing import Optional

import jax
import numpy as np

from ..core.tensor import Tensor
from .dataset import BatchSampler, IterableDataset
from ._worker import (default_collate_fn, fetch as _fetch,  # noqa: F401
                      decode as _decode, worker_loop as _worker_loop)


_obs_handles = None


def _obs():
    """(data_wait_histogram, queue_depth_gauge) — observability handles,
    created once and cached (registry.reset() zeroes values in place, so
    the cache stays valid)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.histogram("dataloader_data_wait_seconds",
                         "time the consumer waited for its next batch "
                         "(the train loop's data-starvation signal)"),
            _m.gauge("dataloader_queue_depth",
                     "device-prefetch queue depth seen at consume time"))
    return _obs_handles


def _default_mp_context() -> str:
    """"fork" when single-threaded (cheap, reference behavior); "forkserver"
    once runtime threads exist — forking a jax/XLA-threaded parent can
    deadlock the child on an inherited lock."""
    if threading.active_count() > 1:
        return "forkserver"
    return "fork"


class _WorkerDied(Exception):
    """Internal: a worker process exited with tasks in flight (respawnable)."""


class _WorkerPool:
    """Worker processes with bounded in-flight tasks + reordering.

    Dead workers (OOM-killed, segfaulted, fault-injected) are respawned —
    with backoff via utils.retry — and their lost tasks resubmitted, up to
    `max_respawns` per epoch (PDTPU_WORKER_RESPAWNS, default 2); only after
    that budget does the epoch fail with UnavailableError.
    """

    def __init__(self, dataset, collate_fn, num_workers, use_shm,
                 worker_init_fn, timeout, mp_context=None,
                 max_respawns=None):
        if mp_context is None or isinstance(mp_context, str):
            method = mp_context or _default_mp_context()
        else:
            method = mp_context.get_start_method()
        self._timeout = timeout if timeout and timeout > 0 else None
        self._epoch = 0
        if max_respawns is None:
            max_respawns = int(os.environ.get("PDTPU_WORKER_RESPAWNS", "2"))
        self._max_respawns = max_respawns
        try:
            self._start(mp.get_context(method), dataset, collate_fn,
                        num_workers, use_shm, worker_init_fn)
        except (AttributeError, TypeError, pickle.PicklingError) as e:
            if method == "fork" or mp_context is not None:
                raise
            # forkserver/spawn needs picklable dataset/collate/init_fn;
            # locally-defined ones force the fork path (reference behavior,
            # at the cost of fork-with-threads deadlock risk)
            warnings.warn(
                f"DataLoader falling back to fork workers: {e} "
                "(make dataset/collate_fn/worker_init_fn module-level "
                "picklables to use the thread-safe forkserver start method)",
                RuntimeWarning)
            self._start(mp.get_context("fork"), dataset, collate_fn,
                        num_workers, use_shm, worker_init_fn)

    def _start(self, ctx, dataset, collate_fn, num_workers, use_shm,
               worker_init_fn):
        self._ctx = ctx
        self._worker_args = (dataset, collate_fn, use_shm, worker_init_fn,
                             num_workers)
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._procs = [self._spawn_worker(wid) for wid in range(num_workers)]
        try:
            for p in self._procs:
                p.start()
        except Exception:
            for p in self._procs:
                if p.is_alive():
                    p.terminate()
            self._procs = []
            raise

    def _spawn_worker(self, wid):
        dataset, collate_fn, use_shm, worker_init_fn, nw = self._worker_args
        # fault config is read HERE (parent, spawn time) and passed as an
        # arg: a forkserver's cached environment must not decide whether
        # the injection is armed — and a respawned worker picks up the
        # config current at respawn time (disarmed once the test clears it)
        from ..utils import faults as _faults
        return self._ctx.Process(
            target=_worker_loop,
            args=(dataset, collate_fn, self._task_q, self._result_q, wid,
                  use_shm, worker_init_fn, nw, _faults.get("worker_crash")),
            daemon=True)

    def respawn_dead(self):
        """Replace every dead worker process; returns how many were
        replaced.  Transient spawn failures (fd/pid exhaustion under load)
        back off and retry via the shared RetryPolicy."""
        from ..utils.monitor import stat_add
        from ..utils.retry import RetryPolicy
        replaced = 0
        policy = RetryPolicy(retries=2, base_delay=0.1, max_delay=1.0,
                             retry_on=(OSError, RuntimeError))
        for i, p in enumerate(self._procs):
            if p.is_alive():
                continue

            def start_one(wid=i):
                q = self._spawn_worker(wid)
                q.start()
                return q
            self._procs[i] = policy.call(start_one)
            replaced += 1
        if replaced:
            stat_add("STAT_dataloader_worker_respawns", replaced)
        return replaced

    def _get_result(self):
        """Blocking result fetch that detects dead workers and honors the
        user timeout with a meaningful error (reference: reader.py raises on
        worker exit; torch detects OOM-killed workers the same way)."""
        waited = 0.0
        while True:
            try:
                return self._result_q.get(timeout=1.0)
            except queue.Empty:
                if not self.alive():
                    raise _WorkerDied()
                waited += 1.0
                if self._timeout is not None and waited >= self._timeout:
                    from ..core.errors import ExecutionTimeoutError
                    raise ExecutionTimeoutError(
                        f"[ExecutionTimeout] DataLoader worker timed out "
                        f"after {waited:.0f}s")

    def run(self, index_batches, max_in_flight):
        """Yield collated numpy batches in order.

        Every task/result carries an epoch id: stale in-flight results from
        an abandoned or failed earlier run (persistent workers) are decoded
        and dropped — decoding frees their shared-memory blocks and keeps
        sequence numbers from colliding across epochs.  A worker death
        respawns the dead workers and resubmits every submitted-but-
        undelivered task; duplicate deliveries (a surviving worker also had
        the task) are decoded and dropped."""
        self._epoch += 1
        epoch = self._epoch
        it = enumerate(index_batches)
        pending = {}
        outstanding = {}  # seq -> indices, submitted but not yet received
        next_seq = 0
        exhausted = False
        respawns_left = self._max_respawns
        try:
            while True:
                while not exhausted and len(outstanding) < max_in_flight:
                    try:
                        seq, idx = next(it)
                    except StopIteration:
                        exhausted = True
                        break
                    idx = list(idx)
                    self._task_q.put((epoch, seq, idx))
                    outstanding[seq] = idx
                if not outstanding and next_seq not in pending:
                    return
                while next_seq not in pending:
                    try:
                        ep, seq, batch, err = self._get_result()
                    except _WorkerDied:
                        if respawns_left <= 0:
                            from ..core.errors import UnavailableError
                            raise UnavailableError(
                                "[Unavailable] DataLoader worker process "
                                "died unexpectedly (killed or crashed) "
                                f"with a task in flight; respawn budget "
                                f"({self._max_respawns}) exhausted")
                        respawns_left -= 1
                        self.respawn_dead()
                        # the dead worker's tasks are lost — resubmit every
                        # undelivered one (dupes from surviving workers are
                        # dropped below)
                        for seq2, idx2 in sorted(outstanding.items()):
                            self._task_q.put((epoch, seq2, idx2))
                        continue
                    if ep != epoch or seq < next_seq or seq in pending:
                        if batch is not None:
                            _decode(batch)  # free stale/duplicate shm
                        continue
                    if err is not None:
                        raise RuntimeError(
                            f"DataLoader worker failed: {err}")
                    pending[seq] = batch
                    outstanding.pop(seq, None)
                yield _decode(pending.pop(next_seq))
                next_seq += 1
        finally:
            # abandoned / failed epoch: free every shm block we can see now;
            # later-arriving strays are freed by the stale-epoch branch above
            # on the next run, or by shutdown()'s drain
            for b in pending.values():
                _decode(b)
            self._drain()

    def _drain(self):
        """Decode-and-discard everything currently in the result queue
        (frees shared-memory blocks whose ownership passed to this side)."""
        while True:
            try:
                _, _, batch, _ = self._result_q.get_nowait()
            except queue.Empty:
                return
            except Exception:
                return
            if batch is not None:
                _decode(batch)

    def shutdown(self):
        import time as _time
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except Exception:
                pass
        # drain WHILE joining: a worker blocked on a full result pipe can
        # only reach its exit sentinel if this side keeps consuming (and
        # decoding frees the shm ownership that was transferred to us)
        deadline = _time.monotonic() + 5.0
        procs = list(self._procs)
        while procs and _time.monotonic() < deadline:
            self._drain()
            procs = [p for p in procs if p.is_alive()]
            if procs:
                procs[0].join(timeout=0.1)
        for p in procs:
            p.terminate()
        self._procs = []
        self._drain()  # workers have exited: anything left is ours to free

    def alive(self):
        return bool(self._procs) and all(p.is_alive() for p in self._procs)


class DataLoader:
    """paddle.io.DataLoader — iterates device-resident batches."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, multiprocessing_context=None):
        from ..core.errors import InvalidArgumentError
        if batch_sampler is None and (not isinstance(batch_size, int)
                                      or batch_size <= 0):
            raise InvalidArgumentError(
                f"[DataLoader] batch_size must be a positive int, got "
                f"{batch_size!r}")
        if not isinstance(num_workers, int) or num_workers < 0:
            raise InvalidArgumentError(
                f"[DataLoader] num_workers must be a non-negative int, got "
                f"{num_workers!r}")
        if timeout and timeout < 0:
            raise InvalidArgumentError(
                f"[DataLoader] timeout must be >= 0, got {timeout!r}")
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.prefetch = self.prefetch_factor if use_buffer_reader else 0
        self.use_shared_memory = use_shared_memory
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.multiprocessing_context = multiprocessing_context
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            if num_workers:
                import warnings
                warnings.warn(
                    "DataLoader(num_workers>0) over an IterableDataset "
                    "runs single-process: parallel workers would need "
                    "stream sharding the dataset does not declare "
                    "(map-style datasets DO use the worker pool)",
                    stacklevel=2)
                self.num_workers = 0
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        self._pool: Optional[_WorkerPool] = None
        self._pool_busy = False
        self._pool_lock = threading.Lock()
        # owned (non-persistent) pools of live iterations, so an abandoned
        # iterator whose producer thread is wedged can still be torn down
        # from close()/__del__ instead of leaking worker processes
        self._owned_pools: set = set()

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no length")
        return len(self.batch_sampler)

    def close(self):
        """Shut down the persistent pool and any owned pools left behind by
        abandoned iterators.  Idempotent; also runs from __del__."""
        with self._pool_lock:
            owned = list(self._owned_pools)
            self._owned_pools.clear()
            pool, self._pool = self._pool, None
            self._pool_busy = False
        for p in owned + ([pool] if pool is not None else []):
            try:
                p.shutdown()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _new_pool(self):
        # transient spawn failures (fd/pid exhaustion on a loaded host)
        # back off and retry; real config errors (unpicklable dataset under
        # an explicit spawn context) surface immediately
        from ..utils.retry import retry_call
        return retry_call(
            _WorkerPool, self.dataset, self.collate_fn, self.num_workers,
            self.use_shared_memory, self.worker_init_fn, self.timeout,
            mp_context=self.multiprocessing_context,
            retries=2, base_delay=0.2, max_delay=2.0,
            retry_on=(OSError,))

    def _acquire_pool(self):
        """Returns (pool, owned): owned pools are shut down by the caller.
        Persistent workers are reused across epochs, but a concurrent second
        iterator over the same loader gets its own temporary pool (the shared
        result queue cannot serve two epochs at once).  The check-and-mark is
        under a lock: two threads iterating one loader must not both claim
        the persistent pool."""
        if not self.persistent_workers:
            return self._new_pool(), True
        with self._pool_lock:
            if self._pool is not None and (self._pool_busy
                                           or not self._pool.alive()):
                if not self._pool_busy:
                    self._pool.shutdown()
                    self._pool = None
                else:  # concurrent iteration: temporary private pool
                    return self._new_pool(), True
            if self._pool is None:
                self._pool = self._new_pool()
            self._pool_busy = True
            return self._pool, False

    def _batches_numpy(self, pool_box=None):
        if self._iterable_mode:
            # workers for iterable datasets would need stream sharding;
            # single-process here (the common map-style path is parallel)
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers > 0:
            pool, owned = self._acquire_pool()
            if owned:
                with self._pool_lock:
                    self._owned_pools.add(pool)
                if pool_box is not None:
                    pool_box.append(pool)
            max_in_flight = self.num_workers * self.prefetch_factor
            try:
                yield from pool.run(self.batch_sampler, max_in_flight)
            finally:
                if owned:
                    with self._pool_lock:
                        self._owned_pools.discard(pool)
                    pool.shutdown()
                else:
                    with self._pool_lock:
                        self._pool_busy = False
        else:
            for idx in self.batch_sampler:
                yield _fetch(self.dataset, idx, self.collate_fn)

    def __iter__(self):
        # device prefetch pipeline (buffered_reader equivalent): stage the
        # next `prefetch` batches onto the device asynchronously.
        from ..utils.monitor import stat_add

        def to_device(np_batch):
            stat_add("STAT_dataloader_batch_count")
            stat_add("STAT_dataloader_bytes",
                     sum(a.nbytes for a in jax.tree_util.tree_leaves(np_batch)
                         if isinstance(a, np.ndarray)))
            return jax.tree_util.tree_map(
                lambda a: Tensor(jax.device_put(a)) if isinstance(a, np.ndarray) else a,
                np_batch)

        wait_h, depth_g = _obs()

        if self.prefetch <= 0:
            gen = self._batches_numpy()
            while True:
                t0 = time.perf_counter()
                try:
                    b = next(gen)
                except StopIteration:
                    return
                wait_h.observe(time.perf_counter() - t0)
                yield to_device(b)

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()

        def put_bounded(item):
            # blocking put that aborts if the consumer has gone away
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        pool_box: list = []

        def producer():
            gen = self._batches_numpy(pool_box)
            try:
                for b in gen:
                    put_bounded(to_device(b))  # device_put is async
                    if stop.is_set():
                        break
            except BaseException as e:  # re-raised on the consumer side
                put_bounded(e)
            finally:
                gen.close()  # runs _batches_numpy's pool cleanup
                put_bounded(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                t0 = time.perf_counter()
                item = q.get()
                wait_h.observe(time.perf_counter() - t0)
                depth_g.set(q.qsize())
                if item is sentinel:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()  # consumer broke early: unblock + clean up producer
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            t.join(timeout=5)
            if t.is_alive():
                # producer wedged (worker fetch stuck past the join budget):
                # don't leak THIS iteration's worker pool until process exit
                # — tear it down from here.  The producer's own cleanup then
                # finds dead queues and exits; its exception is swallowed by
                # put_bounded's stop check.
                from ..utils.monitor import stat_add
                stat_add("STAT_dataloader_forced_pool_teardowns")
                for p in pool_box:
                    with self._pool_lock:
                        self._owned_pools.discard(p)
                    try:
                        p.shutdown()
                    except Exception:
                        pass


class ResumableLoader:
    """Iteration cursor over a DataLoader (or any iterable of batches).

    The missing piece of crash-consistent resume: params/optimizer/rng ride
    in the checkpoint, but without the data position a resumed run replays
    batches it already trained on.  Wrap the loader, checkpoint
    `state_dict()` (TrainStep.save_checkpoint(data_cursor=...)), and after
    `load_state_dict` the first epoch fast-forwards past the already-
    consumed batches — drawing and discarding them, so any deterministic
    sampler (seeded shuffles included) lands on exactly the batch the
    interrupted run would have seen next.

        cursor = ResumableLoader(loader)
        meta = step.restore_checkpoint(ckpt)
        if meta and "data_cursor" in meta:
            cursor.load_state_dict(meta["data_cursor"])
        for batch in cursor:
            ...
    """

    def __init__(self, loader):
        self.loader = loader
        self.epoch = 0
        self.index = 0  # batches consumed in the current epoch
        self._skip = 0

    def state_dict(self) -> dict:
        return {"epoch": self.epoch, "index": self.index}

    def load_state_dict(self, state: dict):
        self.epoch = int(state.get("epoch", 0))
        self.index = 0
        self._skip = int(state.get("index", 0))

    def __iter__(self):
        from ..utils.monitor import stat_add
        # each iteration restarts the loader from batch 0, so the cursor
        # restarts too (a broken-off epoch must not leave a stale index
        # that a later checkpoint would fast-forward past); the load_
        # state_dict fast-forward belongs to the FIRST iteration only
        skip, self._skip = self._skip, 0
        self.index = 0
        for b in self.loader:
            if skip > 0:
                skip -= 1
                self.index += 1
                stat_add("STAT_dataloader_resume_skipped_batches")
                continue
            self.index += 1
            yield b
        self.epoch += 1
        self.index = 0

    def __len__(self):
        return len(self.loader)
