"""Filesystem clients: local + HDFS shell.

Reference: framework/io/fs.cc (C++ shell-out fs used by Dataset/checkpoint)
and python/paddle/distributed/fleet/utils/fs.py (LocalFS/HDFSClient).
TPU-native stance: the host-side services (dataset file lists, checkpoint
upload on preemption) need the same reach; the device path never touches
this.  HDFS access shells out to the `hadoop` CLI exactly like the
reference — gated, with timeout + retry — so it degrades cleanly on
machines without a Hadoop install.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import time
from typing import List, Optional, Sequence, Tuple

__all__ = [
    "LocalFS", "HDFSClient", "ExecuteError", "FSFileExistsError",
    "FSFileNotExistsError", "FSTimeOut",
]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FS:
    """Abstract filesystem. Concrete: LocalFS, HDFSClient."""

    def ls_dir(self, fs_path) -> Tuple[List[str], List[str]]:
        """-> (dirs, files) directly under fs_path."""
        raise NotImplementedError

    def is_file(self, fs_path) -> bool:
        raise NotImplementedError

    def is_dir(self, fs_path) -> bool:
        raise NotImplementedError

    def is_exist(self, fs_path) -> bool:
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self) -> bool:
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path) -> List[str]:
        dirs, _ = self.ls_dir(fs_path)
        return dirs

    def list_files(self, fs_path) -> List[str]:
        _, files = self.ls_dir(fs_path)
        return files

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem with the FS interface (reference LocalFS)."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path, ignore_errors=True)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def upload(self, local_path, fs_path):
        # local->local degenerates to a copy (parity with reference)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path)
        else:
            shutil.copy2(local_path, fs_path)

    download = upload

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if test_exists:
            if not self.is_exist(src_path):
                raise FSFileNotExistsError(src_path)
            if not overwrite and self.is_exist(dst_path):
                raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        d = os.path.dirname(fs_path)
        if d:
            self.mkdirs(d)
        open(fs_path, "a").close()


def _hadoop_available(cmd: str) -> bool:
    return shutil.which(cmd.split()[0]) is not None


class HDFSClient(FS):
    """`hadoop fs` shell client (reference HDFSClient, fs.py:190).

    configs may carry fs.default.name / hadoop.job.ugi which are passed as
    -D options on every invocation.  All calls retry `retry_times` with
    `time_out` ms per attempt, mirroring the reference's shell wrapper.
    """

    def __init__(self, hadoop_home: Optional[str] = None,
                 configs: Optional[dict] = None, time_out: int = 5 * 60 * 1000,
                 sleep_inter: int = 1000, retry_times: int = 3):
        if hadoop_home:
            self._cmd = os.path.join(hadoop_home, "bin", "hadoop")
        else:
            self._cmd = "hadoop"
        self._opts: List[str] = []
        for k, v in (configs or {}).items():
            self._opts += ["-D", f"{k}={v}"]
        self._timeout_s = max(1, time_out // 1000)
        self._sleep_s = max(0.0, sleep_inter / 1000.0)
        self._retries = max(1, retry_times)
        if not _hadoop_available(self._cmd):
            raise ExecuteError(
                f"hadoop binary not found ({self._cmd}); HDFSClient needs a "
                "Hadoop install on the host")

    # -- shell plumbing ---------------------------------------------------
    def _run(self, *args: str, check: bool = True) -> Tuple[int, str]:
        cmd = [self._cmd, "fs"] + self._opts + list(args)
        last = None
        for attempt in range(self._retries):
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=self._timeout_s)
            except subprocess.TimeoutExpired as e:
                last = FSTimeOut(f"{' '.join(cmd)} timed out: {e}")
                time.sleep(self._sleep_s)
                continue
            if proc.returncode == 0 or not check:
                return proc.returncode, proc.stdout
            last = ExecuteError(
                f"{' '.join(cmd)} rc={proc.returncode}: "
                f"{proc.stderr[-500:]}")
            time.sleep(self._sleep_s)
        raise last  # type: ignore[misc]

    # -- FS interface -----------------------------------------------------
    def ls_dir(self, fs_path):
        rc, out = self._run("-ls", fs_path, check=False)
        if rc != 0:
            return [], []
        dirs, files = [], []
        for line in out.splitlines():
            fields = line.split()
            if len(fields) < 8:
                continue
            name = os.path.basename(fields[-1])
            (dirs if fields[0].startswith("d") else files).append(name)
        return dirs, files

    def _test(self, flag: str, fs_path) -> bool:
        rc, _ = self._run("-test", flag, fs_path, check=False)
        return rc == 0

    def is_file(self, fs_path):
        return self._test("-f", fs_path)

    def is_dir(self, fs_path):
        return self._test("-d", fs_path)

    def is_exist(self, fs_path):
        return self._test("-e", fs_path)

    def upload(self, local_path, fs_path):
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(fs_path)
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", "-skipTrash", fs_path)

    def need_upload_download(self):
        return True

    def rename(self, fs_src_path, fs_dst_path):
        self._run("-mv", fs_src_path, fs_dst_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if test_exists:
            if not self.is_exist(fs_src_path):
                raise FSFileNotExistsError(fs_src_path)
            if not overwrite and self.is_exist(fs_dst_path):
                raise FSFileExistsError(fs_dst_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)


def get_fs(path: str) -> FS:
    """Pick a client by scheme: hdfs:// or afs:// -> HDFSClient else LocalFS."""
    if path.startswith(("hdfs://", "afs://")):
        return HDFSClient()
    return LocalFS()
