"""The program store: process-wide owner of JAX's persistent compilation
cache, with a content-addressed key that folds in paddle_tpu's own
semantic versions.

JAX's cache key covers the lowered HLO, compile options, and the
jax/jaxlib versions — but NOT this framework's op semantics: two
paddle_tpu builds whose `utils/op_version` registries differ can lower
byte-identical HLO for an op family whose serialized semantics changed
(the exact hazard the reference's op_version_registry exists for).  The
store therefore namespaces the cache directory by a fingerprint of
(paddle_tpu version, full op_version snapshot, jax version): a version
bump lands in a fresh subdirectory and recompiles — a stale artifact can
never be reused silently, and no artifact is ever invalidated in place.

Knobs (env, read at `ensure_enabled()` / import-time bootstrap):

- ``PDTPU_PROGRAM_CACHE_DIR``            base directory; unset = disabled
- ``PDTPU_PROGRAM_CACHE_MIN_COMPILE_S``  min compile seconds to persist
  (default 0: fleet cold-start wants even the small dispatch-cache
  programs — jax's own 1s default would skip them all)
- ``PDTPU_PROGRAM_CACHE_MAX_BYTES``      LRU cap for the cache dir
  (jax_compilation_cache_max_size; default unlimited)

Corrupt or unreadable entries are a warning + fresh compile, never a
crash (`jax_raise_persistent_cache_errors` is forced off).  Hit/miss
counters come from jax's own monitoring events and surface in
`stats()`, the metrics registry (``program_store_*`` series),
`observability.report()["program_store"]` and the gateway ``/healthz``.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

__all__ = ["ProgramStore", "get_program_store", "enable", "disable",
           "ensure_enabled", "cache_fingerprint", "store_stats"]

_ENV_DIR = "PDTPU_PROGRAM_CACHE_DIR"
_ENV_MIN_COMPILE = "PDTPU_PROGRAM_CACHE_MIN_COMPILE_S"
_ENV_MAX_BYTES = "PDTPU_PROGRAM_CACHE_MAX_BYTES"

# jax monitoring event names (jax/_src/compilation_cache.py)
_EV_HIT = "/jax/compilation_cache/cache_hits"
_EV_MISS = "/jax/compilation_cache/cache_misses"
_EV_REQ = "/jax/compilation_cache/compile_requests_use_cache"


def cache_fingerprint(paddle_version: Optional[str] = None,
                      op_versions: Optional[dict] = None,
                      jax_version: Optional[str] = None) -> str:
    """Content-address for the cache namespace: any change to the
    paddle_tpu version, ANY registered op version, or the jax version
    produces a different fingerprint (= a different subdirectory, = a
    guaranteed miss).  Arguments exist for tests; production callers use
    the live registries."""
    if paddle_version is None:
        from .. import version
        paddle_version = version.full_version
    if op_versions is None:
        from ..utils import op_version
        op_versions = op_version.snapshot()
    if jax_version is None:
        import jax
        jax_version = jax.__version__
    payload = json.dumps(
        {"paddle_tpu": paddle_version, "jax": jax_version,
         "op_versions": dict(sorted(op_versions.items()))},
        sort_keys=True).encode()
    return hashlib.sha256(payload).hexdigest()[:16]


class ProgramStore:
    """Singleton wrapper over jax's persistent compilation cache config
    (use `get_program_store()`; `enable`/`disable`/`stats` module
    functions proxy to it)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._enabled = False
        self._base_dir: Optional[str] = None
        self._dir: Optional[str] = None
        self._fingerprint: Optional[str] = None
        self._saved_config: Optional[dict] = None
        # monitoring-fed counters (events keep firing process-wide; the
        # listener is registered once and gates on _enabled)
        self._hits = 0
        self._misses = 0
        self._requests = 0
        self._listener_registered = False
        self._collector_registered = False
        # disk-scan memo: stats() feeds /healthz and every Prometheus
        # scrape — an O(entries) directory walk per probe would make
        # readiness latency track cache size (bad on fleet-shared NFS)
        self._disk_cache = (0.0, 0, 0)  # (at, entries, bytes)

    # -- lifecycle ---------------------------------------------------------
    def enable(self, cache_dir: Optional[str] = None) -> Optional[str]:
        """Point every XLA compile in this process at the on-disk cache
        under `cache_dir` (or ``PDTPU_PROGRAM_CACHE_DIR``).  Returns the
        fingerprinted directory actually used, or None when no directory
        is configured.  Re-enabling with the same dir is a no-op;
        enabling after compiles already happened works (jax's cache
        memoization is reset)."""
        with self._lock:
            base = cache_dir or os.environ.get(_ENV_DIR)
            if not base:
                return None
            import jax
            fp = cache_fingerprint()
            target = os.path.join(base, f"v-{fp}")
            if self._enabled and self._dir == target:
                return self._dir
            os.makedirs(target, exist_ok=True)
            if self._saved_config is None:
                self._saved_config = {
                    k: getattr(jax.config, k) for k in (
                        "jax_compilation_cache_dir",
                        "jax_persistent_cache_min_entry_size_bytes",
                        "jax_persistent_cache_min_compile_time_secs",
                        "jax_raise_persistent_cache_errors",
                        "jax_compilation_cache_max_size")}
            min_compile = float(os.environ.get(_ENV_MIN_COMPILE, "0") or 0)
            jax.config.update("jax_compilation_cache_dir", target)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              min_compile)
            # corrupt artifact = warning + fresh compile, never a crash
            jax.config.update("jax_raise_persistent_cache_errors", False)
            max_bytes = os.environ.get(_ENV_MAX_BYTES)
            if max_bytes:
                jax.config.update("jax_compilation_cache_max_size",
                                  int(max_bytes))
            self._reset_jax_cache()
            self._base_dir = base
            self._dir = target
            self._fingerprint = fp
            self._enabled = True
            self._disk_cache = (0.0, 0, 0)
            self._register_listener()
            self._register_collector()
            return self._dir

    def disable(self):
        """Restore jax's prior cache config (tests; or turning the store
        off live)."""
        with self._lock:
            if not self._enabled:
                return
            import jax
            for k, v in (self._saved_config or {}).items():
                jax.config.update(k, v)
            self._saved_config = None
            self._enabled = False
            self._dir = None
            self._fingerprint = None
            self._reset_jax_cache()

    def ensure_enabled(self) -> bool:
        """Enable from the environment (the import-time bootstrap and
        the dispatch-cache miss hook): cheap no-op when
        ``PDTPU_PROGRAM_CACHE_DIR`` is unset."""
        with self._lock:
            if self._enabled:
                return True
            if not os.environ.get(_ENV_DIR):
                return False
            return self.enable() is not None

    @staticmethod
    def _reset_jax_cache():
        """jax memoizes is-the-cache-usable at the first compile; reset
        so enabling/disabling AFTER compiles have happened takes effect."""
        try:
            from jax._src import compilation_cache as _cc
            _cc.reset_cache()
        except Exception:
            pass

    # -- telemetry ---------------------------------------------------------
    def _register_listener(self):
        if self._listener_registered:
            return
        try:
            from jax._src import monitoring

            def _on_event(name, **kw):
                if not self._enabled:
                    return
                if name == _EV_HIT:
                    self._hits += 1
                elif name == _EV_MISS:
                    self._misses += 1
                    # a miss means a new entry was just written: drop the
                    # disk-scan memo so stats() reflects it immediately
                    self._disk_cache = (0.0, 0, 0)
                elif name == _EV_REQ:
                    self._requests += 1

            monitoring.register_event_listener(_on_event)
            self._listener_registered = True
        except Exception:
            pass  # older jax: stats degrade to entry counts only

    def _register_collector(self):
        if self._collector_registered:
            return
        try:
            from ..observability.metrics import get_registry

            def _collect():
                s = self.stats()
                return [
                    {"name": "program_store_enabled", "kind": "gauge",
                     "value": 1.0 if s["enabled"] else 0.0,
                     "help": "persistent program store active"},
                    {"name": "program_store_hits_total", "kind": "counter",
                     "value": s["hits"],
                     "help": "persistent-cache compile hits"},
                    {"name": "program_store_misses_total",
                     "kind": "counter", "value": s["misses"],
                     "help": "persistent-cache compile misses (written)"},
                    {"name": "program_store_entries", "kind": "gauge",
                     "value": s["entries"],
                     "help": "executables in the store"},
                    {"name": "program_store_bytes", "kind": "gauge",
                     "value": s["bytes"],
                     "help": "bytes on disk in the store"},
                ]

            get_registry().register_collector(_collect)
            self._collector_registered = True
        except Exception:
            pass

    _DISK_TTL_S = 2.0

    def stats(self) -> dict:
        """One snapshot: config + live hit/miss counters + disk usage.
        The directory scan is memoized for ~2s so health probes and
        metric scrapes stay O(1) against a large (possibly networked)
        cache dir."""
        import time
        with self._lock:
            at, entries, size = self._disk_cache
            now = time.monotonic()
            if self._dir and (now - at > self._DISK_TTL_S or at == 0.0):
                entries = size = 0
                try:
                    with os.scandir(self._dir) as it:
                        for e in it:
                            if e.name.endswith("-cache"):
                                entries += 1
                            try:
                                size += e.stat().st_size
                            except OSError:
                                pass
                except OSError:
                    pass
                self._disk_cache = (now, entries, size)
            elif not self._dir:
                entries = size = 0
            return {"enabled": self._enabled, "dir": self._dir,
                    "fingerprint": self._fingerprint,
                    "entries": entries, "bytes": size,
                    "hits": self._hits, "misses": self._misses,
                    "requests": self._requests}


_store = ProgramStore()


def get_program_store() -> ProgramStore:
    return _store


def enable(cache_dir: Optional[str] = None) -> Optional[str]:
    return _store.enable(cache_dir)


def disable():
    _store.disable()


def ensure_enabled() -> bool:
    return _store.ensure_enabled()


def store_stats() -> dict:
    return _store.stats()
