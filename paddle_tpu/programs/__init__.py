"""paddle_tpu.programs — the program-lifecycle layer.

The reference framework's whole reason for a static ``ProgramDesc`` world
was that programs are built once and executed many times (PAPER.md §1);
this package restores that property ACROSS PROCESSES for the JAX rebuild.
Three subsystems compile XLA programs independently — the eager dispatch
cache (`core/op.py`), `TrainStep`/`jit` builds, and the serving
prefill/decode/verify family — and before this layer every process paid
full cold-start tracing + XLA compilation for each of them: the
fleet-scale poison when thousands of serving replicas boot.

Two pillars:

- **store** — one process-wide program store wrapping JAX's persistent
  compilation cache.  `store.enable(dir)` (or the
  ``PDTPU_PROGRAM_CACHE_DIR`` env knob, picked up automatically at
  import) points every XLA compile in the process — dispatch-cache
  misses, TrainStep builds, serving programs — at a shared on-disk
  cache.  The cache directory is CONTENT-ADDRESSED: the paddle_tpu
  version and the full `utils/op_version` snapshot are folded into a
  fingerprint subdirectory, so an artifact compiled under different op
  semantics can never be reused silently — a version bump simply lands
  in a fresh subdir and recompiles.  Corrupt entries fall back to a
  fresh compile (never a crash).  Hit/miss counters feed
  `observability.report()` and the gateway's `/healthz`.
- **program_set** — AOT serialization of a serving engine's ENTIRE
  program family (per-bucket prefill, decode or speculative verify,
  paged variants) as ONE on-disk artifact with its bucket/mesh/
  quantize/spec configuration manifest.  Each program is stored twice:
  as a serialized native XLA executable (zero tracing + zero compile on
  load; exact jax-version + topology match required) and as portable
  StableHLO (`jax.export`; compiled on load, persistent-cache
  accelerated).  `ServingEngine(..., program_set=path)` /
  `Config.enable_serving(..., program_set=path)` boot warm without
  retracing; a manifest mismatch (weights, buckets, spec/quantize/mesh
  config, op versions) is a typed `ProgramSetError`, never silent
  reuse.

Warmup rides on top: `ServingEngine.warmup()` precompiles every program
in the set before traffic and snapshots the compiled-program registry so
`post_warmup_compiles()` can assert the fleet contract (zero compiles
under mixed traffic); `TrainStep.warmup(batch)` /
`ShardedTrainStep.warmup(batch)` AOT-compile the step for a sample batch
without applying an update.

Quick use::

    export PDTPU_PROGRAM_CACHE_DIR=/var/cache/paddle_tpu   # fleet knob

    # or in-process:
    from paddle_tpu import programs
    programs.enable("/var/cache/paddle_tpu")
    ...
    print(programs.store_stats())   # {hits, misses, entries, bytes, ...}

    # AOT program set for a serving replica fleet:
    predictor.save_program_set("gpt.pdprograms")        # once, anywhere
    cfg.enable_serving(model_provider=build,
                       program_set="gpt.pdprograms")    # every replica
"""
from __future__ import annotations

from .store import (ProgramStore, enable, disable, ensure_enabled,  # noqa: F401
                    get_program_store, cache_fingerprint, store_stats)
from .program_set import (ProgramSetError, save_program_set,  # noqa: F401
                          load_program_set, read_manifest)

__all__ = [
    "ProgramStore", "enable", "disable", "ensure_enabled",
    "get_program_store", "cache_fingerprint", "store_stats",
    "ProgramSetError", "save_program_set", "load_program_set",
    "read_manifest", "bootstrap",
]


def bootstrap():
    """Import-time hook (called from paddle_tpu/__init__): enable the
    store when ``PDTPU_PROGRAM_CACHE_DIR`` is set — a no-op otherwise,
    so processes that never opt in pay nothing."""
    try:
        ensure_enabled()
    except Exception:  # the store must never break import
        pass
