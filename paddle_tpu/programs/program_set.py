"""AOT program sets: a serving engine's entire compiled-program family as
one on-disk artifact.

`jit.save` exports ONE model forward; a serving engine runs a FAMILY —
one prefill per prompt-length bucket (covering target + draft on a
speculative engine), plus the single decode (or verify) step, in fixed or
paged KV layout.  `save_program_set` captures the whole family with its
configuration manifest; `ServingEngine(..., program_set=path)` /
`Config.enable_serving(..., program_set=path)` boots from it WITHOUT
retracing any model code.

Each program is stored in two representations, tried in order at load:

- ``exe`` — the serialized native XLA executable
  (`jax.experimental.serialize_executable`): zero tracing AND zero XLA
  compilation on load — the fastest possible boot.  Valid only for the
  exact jax version, backend and device topology recorded in the
  manifest (a compiled binary is not an interchange format).
- ``stablehlo`` — the portable `jax.export` serialization: survives
  jax-version drift within jax's export-compat window, and — for
  UNMESHED engines — backend/device-count drift too (mesh engines bake
  a device assignment, so their manifest gates topology for BOTH
  representations); loading compiles the StableHLO (accelerated by the
  persistent program store).  `jax.export` does not carry buffer
  donation, so the loader re-applies each program's recorded
  ``donate_argnums`` through an outer `jax.jit` — without it every
  serving tick would silently copy the whole KV pool.

Staleness can never be silent: the manifest embeds the paddle_tpu
version, the full `utils/op_version` snapshot, hashes of the target (and
draft) weight shapes/dtypes, and every engine knob that shapes a program
(buckets, slots, lengths, decode chunk, spec_tokens, kv layout,
block_size/num_blocks, mesh axes, pool dtype).  Any mismatch — or a
byte-corrupted artifact (sha256-checked before unpickling) — raises the
typed `ProgramSetError`; `inference.ServingPredictor` catches it, warns,
counts it (``program_set_fallback_total``) and falls back to a fresh
trace+compile.
"""
from __future__ import annotations

import hashlib
import os
import pickle
from typing import Dict, Optional

__all__ = ["ProgramSetError", "save_program_set", "load_program_set",
           "read_manifest", "LoadedProgram", "engine_manifest",
           "PROGRAM_SET_SUFFIX"]

PROGRAM_SET_FORMAT = 1
PROGRAM_SET_SUFFIX = ".pdprograms"


class ProgramSetError(RuntimeError):
    """Typed load/save failure: manifest mismatch, corrupt artifact,
    unloadable programs.  Callers may catch it and fall back to a fresh
    trace+compile — the one thing they must never do is reuse a stale
    artifact silently."""


class LoadedProgram:
    """One deserialized program: `fn(*args)` runs it.  ``kind`` records
    which representation loaded — 'exe' programs are ALREADY compiled
    (warmup can skip executing them), 'stablehlo' programs compile on
    their first call."""

    __slots__ = ("name", "kind", "fn")

    def __init__(self, name: str, kind: str, fn):
        self.name = name
        self.kind = kind
        self.fn = fn

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)


def _state_sig(state: Dict) -> str:
    import numpy as np
    items = sorted((k, tuple(int(d) for d in np.shape(v)),
                    str(getattr(v, "dtype", type(v).__name__)))
                   for k, v in state.items())
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def engine_manifest(engine) -> dict:
    """Every config axis that shapes a compiled serving program.  Two
    engines with equal manifests trace byte-identical programs; any
    difference (weight dtype/shape, quantize, spec, mesh, kv layout, op
    semantics) MUST miss."""
    import jax
    from .. import version
    from ..utils import op_version
    mesh = None
    if engine.mesh is not None:
        mesh = {"axes": {k: int(v) for k, v in engine.mesh.shape.items()},
                "devices": int(engine.mesh.devices.size)}
    return {
        "paddle_tpu_version": version.full_version,
        "op_versions": op_version.snapshot(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": int(jax.device_count()),
        "model_class": type(engine.model).__name__,
        "state_sig": _state_sig(engine._state),
        "draft_state_sig": (_state_sig(engine._dstate)
                            if engine.draft_model is not None else None),
        "max_slots": engine.max_slots,
        "max_len": engine.max_len,
        "pool_len": engine._pool_len,
        "buckets": tuple(engine.buckets),
        "decode_chunk": engine.decode_chunk,
        "pad_token_id": engine.pad_token_id,
        "spec_tokens": (engine.spec_tokens
                        if engine.draft_model is not None else None),
        "kv": engine.kv,
        "block_size": (engine.block_size if engine.kv == "paged" else None),
        "num_blocks": (engine.kv_pool.num_blocks
                       if engine.kv == "paged" else None),
        "mesh": mesh,
        "dtype": (str(engine._dtype) if engine._dtype is not None else None),
        "key_width": engine._key_width,
        # batched LoRA bakes the factor-stack avals (rank, slot count,
        # wrapped layer set) into every program signature; adapter IDs
        # and contents are dynamic and deliberately absent
        "lora": (None if getattr(engine, "lora", None) is None else {
            "rank": engine.lora.rank,
            "max_adapters": engine.lora.max_adapters,
            "targets": list(engine.lora.targets),
        }),
    }


# manifest keys whose mismatch only disqualifies the native-executable
# representation (the portable StableHLO one survives them).  backend /
# device_count are exe-only for UNMESHED engines; a mesh engine's
# programs bake a device assignment, so topology gates both
# representations there.
_EXE_ONLY_KEYS = ("jax_version", "backend", "device_count")


def _manifest_mismatches(saved: dict, live: dict) -> list:
    bad = []
    mesh_bound = live.get("mesh") is not None or saved.get("mesh") is not None
    for k in live:
        if k in _EXE_ONLY_KEYS and not (
                mesh_bound and k in ("backend", "device_count")):
            continue
        if saved.get(k) != live[k]:
            bad.append(f"{k}: artifact={saved.get(k)!r} != "
                       f"engine={live[k]!r}")
    return bad


def _export_one(raw_jitted, tracked, args):
    """(exe_blob | None, stablehlo_blob | None, errors) for one program.
    The native executable is taken from the TrackedJit's AOT cache when
    the program is already compiled (warmup ran), so saving a warm
    engine recompiles nothing."""
    errors = {}
    exe_blob = stablehlo_blob = None
    try:
        from jax.experimental import serialize_executable as _sx
        compiled = None
        if tracked is not None and hasattr(tracked, "compiled_for"):
            compiled = tracked.compiled_for(*args)
        if compiled is None:
            compiled = raw_jitted.lower(*args).compile()
        exe_blob = pickle.dumps(_sx.serialize(compiled),
                                protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:  # noqa: BLE001 — representation is optional
        errors["exe"] = f"{type(e).__name__}: {e}"[:300]
    try:
        from jax import export as jax_export
        stablehlo_blob = jax_export.export(raw_jitted)(*args).serialize()
    except Exception as e:  # noqa: BLE001
        errors["stablehlo"] = f"{type(e).__name__}: {e}"[:300]
    return exe_blob, stablehlo_blob, errors


def save_program_set(engine, path: str,
                     extra_meta: Optional[dict] = None) -> str:
    """Serialize the engine's whole program family to
    ``path + '.pdprograms'``.  Engine trace counters are snapshotted and
    restored (export re-traces; that must not look like extra serving
    compiles to `compile_counts()`).  Returns the artifact path."""
    family = engine._program_family()
    # export re-runs the traced python (host-side trace counters fire)
    compiles_snapshot = {"decode": engine._compiles["decode"],
                         "prefill": dict(engine._compiles["prefill"])}
    programs = {}
    save_errors = {}
    try:
        for name, fn, args, donate in family:
            raw = getattr(fn, "_jitted", fn)
            if isinstance(fn, LoadedProgram) or not hasattr(raw, "lower"):
                raise ProgramSetError(
                    f"program {name!r} was itself loaded from a program "
                    "set — re-exporting a loaded set is not supported; "
                    "save from a traced engine")
            exe_blob, hlo_blob, errors = _export_one(raw, fn, args)
            if exe_blob is None and hlo_blob is None:
                raise ProgramSetError(
                    f"program {name!r} could not be serialized in any "
                    f"representation: {errors}")
            if errors:
                save_errors[name] = errors
            programs[name] = {"exe": exe_blob, "stablehlo": hlo_blob,
                              "donate": tuple(donate)}
    finally:
        engine._compiles["decode"] = compiles_snapshot["decode"]
        engine._compiles["prefill"].update(compiles_snapshot["prefill"])
    body = pickle.dumps(
        {"manifest": engine_manifest(engine),
         "extra_meta": dict(extra_meta or {}),
         "save_errors": save_errors,
         "programs": programs},
        protocol=pickle.HIGHEST_PROTOCOL)
    if not path.endswith(PROGRAM_SET_SUFFIX):
        path = path + PROGRAM_SET_SUFFIX
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump({"format": PROGRAM_SET_FORMAT,
                     "sha256": hashlib.sha256(body).hexdigest(),
                     "body": body}, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)  # atomic publish (the checkpoint discipline)
    return path


def _read_body(path: str) -> dict:
    if not path.endswith(PROGRAM_SET_SUFFIX) and not os.path.exists(path):
        path = path + PROGRAM_SET_SUFFIX
    try:
        with open(path, "rb") as f:
            envelope = pickle.load(f)
    except Exception as e:
        raise ProgramSetError(
            f"program set {path!r} unreadable: "
            f"{type(e).__name__}: {e}") from e
    if not isinstance(envelope, dict) or "body" not in envelope:
        raise ProgramSetError(f"program set {path!r}: not a program-set "
                              "artifact")
    if envelope.get("format") != PROGRAM_SET_FORMAT:
        raise ProgramSetError(
            f"program set {path!r}: format {envelope.get('format')!r} "
            f"unsupported (this build reads {PROGRAM_SET_FORMAT})")
    body = envelope["body"]
    digest = hashlib.sha256(body).hexdigest()
    if digest != envelope.get("sha256"):
        raise ProgramSetError(
            f"program set {path!r}: checksum mismatch (corrupt artifact) "
            "— refusing to load; delete it and re-save")
    try:
        return pickle.loads(body)
    except Exception as e:
        raise ProgramSetError(
            f"program set {path!r}: body undecodable: "
            f"{type(e).__name__}: {e}") from e


def read_manifest(path: str) -> dict:
    """The artifact's manifest + save metadata without loading programs."""
    body = _read_body(path)
    return {"manifest": body["manifest"],
            "extra_meta": body.get("extra_meta", {}),
            "save_errors": body.get("save_errors", {}),
            "programs": sorted(body["programs"])}


def _load_one(name: str, rec: dict, exe_ok: bool) -> LoadedProgram:
    errors = {}
    if exe_ok and rec.get("exe") is not None:
        try:
            from jax.experimental import serialize_executable as _sx
            payload = pickle.loads(rec["exe"])
            compiled = _sx.deserialize_and_load(*payload)
            return LoadedProgram(name, "exe", compiled)
        except Exception as e:  # noqa: BLE001 — fall through to stablehlo
            errors["exe"] = f"{type(e).__name__}: {e}"[:300]
    if rec.get("stablehlo") is not None:
        try:
            import jax
            from jax import export as jax_export
            exported = jax_export.deserialize(rec["stablehlo"])
            # jax.export drops donation: re-apply the recorded indices
            # through an outer jit so the KV pool keeps updating in
            # place (a silent donation loss = a full pool copy per tick)
            donate = tuple(rec.get("donate") or ())
            fn = jax.jit(lambda *a, _ex=exported: _ex.call(*a),
                         donate_argnums=donate)
            return LoadedProgram(name, "stablehlo", fn)
        except Exception as e:  # noqa: BLE001
            errors["stablehlo"] = f"{type(e).__name__}: {e}"[:300]
    raise ProgramSetError(
        f"program {name!r} could not be loaded from any representation: "
        f"{errors or 'no representations in artifact'}")


def load_program_set(path: str, engine) -> Dict[str, LoadedProgram]:
    """Validate the artifact against the live engine and deserialize its
    programs.  Loading is deliberately SERIAL: executable
    deserialization contends on a process-wide XLA/LLVM lock, and
    thread-pooling it measures ~3x SLOWER wall-clock than one-at-a-time
    on CPU.  Raises `ProgramSetError` on ANY mismatch or corruption."""
    body = _read_body(path)
    live = engine_manifest(engine)
    saved = body["manifest"]
    mismatches = _manifest_mismatches(saved, live)
    if mismatches:
        raise ProgramSetError(
            "program set does not match this engine/runtime (stale "
            "artifacts are never reused silently): "
            + "; ".join(mismatches[:6]))
    wanted = [name for name, _, _, _ in engine._program_family()]
    missing = [n for n in wanted if n not in body["programs"]]
    if missing:
        raise ProgramSetError(
            f"program set lacks programs {missing} required by this "
            "engine configuration")
    # native executables are version- AND topology-bound; StableHLO only
    # needs the (already-validated) manifest
    exe_ok = all(saved.get(k) == live.get(k) for k in _EXE_ONLY_KEYS)
    out: Dict[str, LoadedProgram] = {}
    errors = {}
    for n in wanted:
        try:
            out[n] = _load_one(n, body["programs"][n], exe_ok)
        except ProgramSetError as e:
            errors[n] = str(e)
    if errors:
        raise ProgramSetError(f"program set load failed: {errors}")
    return out
