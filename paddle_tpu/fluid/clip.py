"""fluid.clip alias module (reference: python/paddle/fluid/clip.py
__all__): era spellings over nn.clip."""
from ..nn.clip import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
    ErrorClipByValue, set_gradient_clip,
)

GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm

__all__ = ["set_gradient_clip", "ErrorClipByValue", "ClipGradByValue",
           "ClipGradByNorm", "ClipGradByGlobalNorm"]
