"""fluid.layers learning-rate decay functional family (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py __all__ =
exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, noam_decay, cosine_decay,
linear_lr_warmup).

Era contract: each returns a decayed learning rate driven by the global
step counter (@LR_DECAY_COUNTER@).  TPU-native: each returns an
`optimizer.lr.LRScheduler` implementing the exact reference formula —
the object plugs into `paddle.optimizer.*(learning_rate=...)` the way the
reference's Variable plugged into fluid optimizers, and `scheduler.step()`
is the step counter.
"""
from __future__ import annotations

import math

from ..optimizer import lr as _lr

__all__ = [
    "exponential_decay", "natural_exp_decay", "inverse_time_decay",
    "polynomial_decay", "piecewise_decay", "noam_decay", "cosine_decay",
    "linear_lr_warmup",
]


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr * d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (learning_rate_scheduler.py:53)."""
    return _lr.NoamDecay(d_model, warmup_steps, learning_rate=learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * decay_rate^(step/decay_steps), floored when staircase
    (learning_rate_scheduler.py:113)."""
    def lam(step):
        div = step / float(decay_steps)
        return decay_rate ** (math.floor(div) if staircase else div)
    return _lr.LambdaDecay(learning_rate, lam)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    """lr * exp(-decay_rate * step/decay_steps)
    (learning_rate_scheduler.py:174)."""
    def lam(step):
        div = step / float(decay_steps)
        return math.exp(-decay_rate * (math.floor(div) if staircase
                                       else div))
    return _lr.LambdaDecay(learning_rate, lam)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    """lr / (1 + decay_rate * step/decay_steps)
    (learning_rate_scheduler.py:235)."""
    def lam(step):
        div = step / float(decay_steps)
        return 1.0 / (1.0 + decay_rate * (math.floor(div) if staircase
                                          else div))
    return _lr.LambdaDecay(learning_rate, lam)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    """(lr - end) * (1 - step/decay_steps)^power + end
    (learning_rate_scheduler.py:296)."""
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries, values):
    """Step function over step-count boundaries
    (learning_rate_scheduler.py:378)."""
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr * 0.5 * (cos(floor(step/step_each_epoch) * pi / epochs) + 1)
    (learning_rate_scheduler.py:444)."""
    def lam(step):
        cur_epoch = math.floor(step / float(step_each_epoch))
        return 0.5 * (math.cos(cur_epoch * math.pi / epochs) + 1)
    return _lr.LambdaDecay(learning_rate, lam)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """start_lr -> end_lr over warmup_steps, then learning_rate (float or
    another scheduler) (learning_rate_scheduler.py:490)."""
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)
