"""fluid.io alias module (reference: python/paddle/fluid/io.py) — save /
load / inference-model entry points over the 2.0 io + jit homes."""
from __future__ import annotations

from ..framework import save, load  # noqa: F401
from ..utils.checkpoint import (  # noqa: F401
    save as save_dygraph, load as load_dygraph,
)
from ..jit import (  # noqa: F401
    save as save_inference_model, load as load_inference_model,
)
from ..io import DataLoader  # noqa: F401


def save_params(executor, dirname, main_program=None, filename=None):
    """Static-era save_params: persist the tracked program state."""
    from ..static import default_main_program
    prog = main_program or default_main_program()
    save(prog._state, dirname if filename is None
         else f"{dirname}/{filename}")


def load_params(executor, dirname, main_program=None, filename=None):
    from ..static import default_main_program
    prog = main_program or default_main_program()
    prog._state = load(dirname if filename is None
                       else f"{dirname}/{filename}", return_numpy=True)
