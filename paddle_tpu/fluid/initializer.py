"""fluid.initializer alias module (reference: python/paddle/fluid/
initializer.py __all__): era spellings over nn.initializer.  Xavier/MSRA
take the era `uniform` flag and resolve to the Normal/Uniform 2.0 pair."""
from __future__ import annotations

from ..nn.initializer import (  # noqa: F401
    Assign, Bilinear, Constant, Normal, TruncatedNormal, Uniform,
    XavierNormal, XavierUniform, KaimingNormal, KaimingUniform,
    set_global_initializer,
)

__all__ = [
    "Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
    "Bilinear", "MSRA", "ConstantInitializer", "UniformInitializer",
    "NormalInitializer", "TruncatedNormalInitializer", "XavierInitializer",
    "BilinearInitializer", "MSRAInitializer", "NumpyArrayInitializer",
    "set_global_initializer",
]


def Xavier(uniform=True, fan_in=None, fan_out=None, seed=0):  # noqa: N802
    """Era factory (reference initializer.py XavierInitializer: one class
    with a uniform flag; 2.0 split it into XavierUniform/XavierNormal)."""
    cls = XavierUniform if uniform else XavierNormal
    return cls(fan_in=fan_in, fan_out=fan_out)


def MSRA(uniform=True, fan_in=None, seed=0):  # noqa: N802
    """Era factory (reference MSRAInitializer -> Kaiming pair)."""
    cls = KaimingUniform if uniform else KaimingNormal
    return cls(fan_in=fan_in)


ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
XavierInitializer = Xavier
BilinearInitializer = Bilinear
MSRAInitializer = MSRA
NumpyArrayInitializer = Assign
