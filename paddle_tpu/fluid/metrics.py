"""fluid.metrics alias module (reference: python/paddle/fluid/metrics.py
__all__ = MetricBase, CompositeMetric, Precision, Recall, Accuracy,
ChunkEvaluator, EditDistance, DetectionMAP, Auc).

The era classes are host-side numpy ACCUMULATORS with update()/eval()
(different surface from the 2.0 paddle.metric classes, which are
batch-metric objects with update/accumulate): Accuracy takes
(value, weight) pairs, Precision/Recall take binary preds/labels,
ChunkEvaluator takes the chunk_eval op's count outputs, EditDistance the
edit_distance op's outputs.  DetectionMAP here is an eager mAP
accumulator over the padded detection_output rows instead of the
reference's in-program detection_map op."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, unwrap

__all__ = ["MetricBase", "CompositeMetric", "Precision", "Recall",
           "Accuracy", "ChunkEvaluator", "EditDistance", "DetectionMAP",
           "Auc"]

from ..metric import Auc as _Auc20  # noqa: E402


class Auc(_Auc20):
    """Era surface over the 2.0 streaming Auc: same update(preds, labels)
    accumulation, plus the era eval() spelling."""

    def eval(self):  # noqa: A003
        return self.accumulate()


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(unwrap(x))
    return np.asarray(x)


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_") and not k.startswith("__"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, type(v)(0))
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def get_config(self):
        return {k: v for k, v in self.__dict__.items()
                if not k.startswith("_")}

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):  # noqa: A003
        raise NotImplementedError


class CompositeMetric(MetricBase):
    """Hold several metrics updated with the same inputs; eval returns
    their results in add order."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):  # noqa: A003
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    """Weighted running mean of per-batch accuracy values."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        value = float(np.asarray(_np(value)).reshape(-1)[0])
        weight = float(weight)
        if weight < 0:
            raise ValueError("weight must be nonnegative")
        self.value += value * weight
        self.weight += weight

    def eval(self):  # noqa: A003
        if self.weight == 0:
            raise ValueError("no batches accumulated — call update first")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision over thresholded preds (era contract: preds are
    probabilities, labels 0/1; rounded at 0.5)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fp += int(np.sum((p == 1) & (l == 0)))

    def eval(self):  # noqa: A003
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (_np(preds).reshape(-1) > 0.5).astype(np.int64)
        l = _np(labels).reshape(-1).astype(np.int64)  # noqa: E741
        self.tp += int(np.sum((p == 1) & (l == 1)))
        self.fn += int(np.sum((p == 0) & (l == 1)))

    def eval(self):  # noqa: A003
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulate the (num_infer, num_label, num_correct) chunk counts the
    chunk_eval op emits; eval -> (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(_np(num_infer_chunks).reshape(-1)[0])
        self.num_label_chunks += int(_np(num_label_chunks).reshape(-1)[0])
        self.num_correct_chunks += int(
            _np(num_correct_chunks).reshape(-1)[0])

    def eval(self):  # noqa: A003
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """Accumulate the edit_distance op's (distances, seq_num) outputs;
    eval -> (avg distance, instance error rate)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = _np(distances).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(_np(seq_num).reshape(-1)[0])
        self.instance_error += int(np.sum(d != 0))

    def eval(self):  # noqa: A003
        if self.seq_num == 0:
            raise ValueError("no sequences accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class DetectionMAP(MetricBase):
    """Eager mean-average-precision accumulator over padded detection
    rows (the reference builds an in-program detection_map op instead;
    fluid/metrics.py DetectionMAP).  update() takes detection_output's
    (B, K, 6) [label, score, x1, y1, x2, y2] rows + counts and the padded
    ground truth; eval() computes 11-point or integral mAP."""

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self._dets = []   # (label, score, iou-matched flag) per image
        self._npos = {}

    def reset(self):
        self._dets = []
        self._npos = {}

    def update(self, nmsed_out, counts, gt_box, gt_label, gt_count=None,
               difficult=None):
        det = _np(nmsed_out)
        cnt = _np(counts).astype(np.int64)
        gb = _np(gt_box)
        gl = _np(gt_label).astype(np.int64)
        if gl.ndim == 3:
            gl = gl[..., 0]
        gc = (_np(gt_count).astype(np.int64) if gt_count is not None
              else np.full(gb.shape[0], gb.shape[1], np.int64))
        if difficult is not None:
            df = _np(difficult).astype(bool)
            if df.ndim == 3:
                df = df[..., 0]
        else:
            df = np.zeros(gl.shape, bool)
        count_difficult = self.evaluate_difficult
        for b in range(det.shape[0]):
            boxes_gt = gb[b, :gc[b]]
            labels_gt = gl[b, :gc[b]]
            diff_gt = df[b, :gc[b]]
            for j, c in enumerate(labels_gt):
                if count_difficult or not diff_gt[j]:
                    self._npos[int(c)] = self._npos.get(int(c), 0) + 1
            used = np.zeros(gc[b], bool)
            rows = det[b, :cnt[b]]
            for lab, score, x1, y1, x2, y2 in rows:
                best_iou, best_j = 0.0, -1
                for j in range(gc[b]):
                    if used[j] or labels_gt[j] != int(lab):
                        continue
                    bx = boxes_gt[j]
                    ix1, iy1 = max(x1, bx[0]), max(y1, bx[1])
                    ix2, iy2 = min(x2, bx[2]), min(y2, bx[3])
                    inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
                    union = ((x2 - x1) * (y2 - y1)
                             + (bx[2] - bx[0]) * (bx[3] - bx[1]) - inter)
                    iou = inter / union if union > 0 else 0.0
                    if iou > best_iou:
                        best_iou, best_j = iou, j
                matched = (best_iou >= self.overlap_threshold
                           and best_j >= 0)
                if matched:
                    used[best_j] = True
                    if not count_difficult and diff_gt[best_j]:
                        # VOC convention: a detection matching a difficult
                        # box is IGNORED (neither TP nor FP)
                        continue
                self._dets.append((int(lab), float(score), bool(matched)))

    def eval(self):  # noqa: A003
        aps = []
        for c, npos in self._npos.items():
            recs = sorted((d for d in self._dets if d[0] == c),
                          key=lambda d: -d[1])
            tps = np.cumsum([d[2] for d in recs]) if recs else np.array([])
            fps = np.cumsum([not d[2] for d in recs]) if recs \
                else np.array([])
            if len(recs) == 0 or npos == 0:
                aps.append(0.0)
                continue
            rec = tps / npos
            prec = tps / np.maximum(tps + fps, 1e-12)
            if self.ap_version == "11point":
                ap = float(np.mean([
                    prec[rec >= t].max() if np.any(rec >= t) else 0.0
                    for t in np.linspace(0, 1, 11)]))
            else:  # integral
                mrec = np.concatenate([[0.0], rec, [1.0]])
                mpre = np.concatenate([[0.0], prec, [0.0]])
                for i in range(len(mpre) - 2, -1, -1):
                    mpre[i] = max(mpre[i], mpre[i + 1])
                idx = np.nonzero(mrec[1:] != mrec[:-1])[0]
                ap = float(np.sum(
                    (mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0

    get_map_var = eval