"""fluid.layers long tail: the remaining nn.py / control_flow.py /
loss.py / sequence_lod.py / tensor.py / io.py names.

Reference: python/paddle/fluid/layers/{nn,control_flow,loss,sequence_lod,
tensor,io}.py.  Split by kind: pure ALIASES to 2.0 homes, small direct
implementations of ops with no 2.0 successor, and (for the LoD/program
machinery masked-dense tracing genuinely subsumes — py_reader,
reorder_lod_tensor_by_rank) explicit UnimplementedError pointers to the
modern path, so ports fail loudly with guidance instead of silently
misbehaving.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import InvalidArgumentError, UnimplementedError
from ..core.op import dispatch
from ..core.tensor import Tensor, unwrap

# --- straight aliases ----------------------------------------------------
from ..nn.functional import (  # noqa: F401
    grid_sample as grid_sampler,
    hardsigmoid as hard_sigmoid,
    hardswish as hard_swish,
)
from ..metric import mean_iou, chunk_eval  # noqa: F401
from ..distribution import sampling_id  # noqa: F401
from ..compat import get_tensor_from_selected_rows  # noqa: F401
from ..tensor.math import add_n as sums  # noqa: F401
# NOTE: fluid.layers.range is wired in layers.py — aliasing it HERE would
# shadow the builtin for every loop in this module
from ..nn.functional.loss import kl_div as kldiv_loss  # noqa: F401
from ..nn.functional.crf import hsigmoid_loss as hsigmoid  # noqa: F401


def adaptive_pool2d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    fn = (F.adaptive_max_pool2d if pool_type == "max"
          else F.adaptive_avg_pool2d)
    return fn(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type="max",  # noqa: A002
                    require_index=False, name=None):
    from ..nn import functional as F
    fn = (F.adaptive_max_pool3d if pool_type == "max"
          else F.adaptive_avg_pool3d)
    return fn(input, pool_size)


_step_counters = {}


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Per-name python counter (the reference's persistable counter var)."""
    key = counter_name or "@STEP_COUNTER@"
    val = _step_counters.get(key, begin - step) + step
    _step_counters[key] = val
    return Tensor(jnp.asarray([val], jnp.int64), stop_gradient=True)


def bilinear_tensor_product(x, y, size, weight=None, bias=None,
                            act=None, name=None, **_ignored):
    """x^T W_k y per output k (reference nn.py bilinear_tensor_product);
    weight (size, dx, dy) explicit per the repo's fluid convention."""
    if weight is None:
        raise InvalidArgumentError(
            "bilinear_tensor_product: pass `weight` explicitly or use "
            "nn.Bilinear / legacy_layers.BilinearTensorProduct")

    def raw(xv, yv, wv, bv):
        out = jnp.einsum("bi,kij,bj->bk", xv, wv, yv)
        if bv is not None:
            out = out + bv.reshape(1, -1)
        return out

    return dispatch("bilinear_tensor_product", raw, x, y, weight, bias)


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return dispatch("brelu", lambda v: jnp.clip(v, t_min, t_max), x)


def continuous_value_model(input, cvm, use_cvm=True, name=None):  # noqa: A002
    """CVM op (reference cvm_op.cc): with use_cvm the first two columns
    (show/click) are replaced by the log-transformed cvm input; without
    it they are stripped."""
    def raw(xv, cv):
        if use_cvm:
            logs = jnp.log(jnp.maximum(cv, 0.0) + 1.0)
            return jnp.concatenate([logs[:, :2], xv[:, 2:]], axis=1)
        return xv[:, 2:]

    return dispatch("cvm", raw, input, cvm)


def cos_sim(X, Y, name=None):  # noqa: N803
    from ..nn.functional import cosine_similarity
    out = cosine_similarity(X, Y, axis=1)
    from ..tensor.manipulation import reshape
    return reshape(out, [-1, 1])


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Keep rows whose tag set intersects filter_tag (reference
    filter_by_instag_op, the ad-ranking instag filter).  Host-side data
    prep; returns (filtered_rows, kept_index, loss_weight)."""
    tags = np.asarray(jax.device_get(unwrap(ins_tag))).reshape(len(
        np.asarray(jax.device_get(unwrap(ins)))), -1)
    want = set(np.asarray(jax.device_get(unwrap(filter_tag))).reshape(-1)
               .tolist())
    keep = [i for i in range(tags.shape[0])
            if set(tags[i].tolist()) & want]
    xv = unwrap(ins)
    if not keep:
        empty = jnp.full((1,) + xv.shape[1:], out_val_if_empty, xv.dtype)
        return (Tensor(empty, stop_gradient=True),
                Tensor(jnp.zeros((1,), jnp.int64), stop_gradient=True),
                Tensor(jnp.zeros((1, 1), jnp.float32), stop_gradient=True))
    idx = jnp.asarray(keep, jnp.int32)
    out = dispatch("filter_by_instag", lambda v: v[idx], ins)
    return (out, Tensor(idx.astype(jnp.int64), stop_gradient=True),
            Tensor(jnp.ones((len(keep), 1), jnp.float32),
                   stop_gradient=True))


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    from ..tensor.random import normal
    return normal(mean=mean, std=std, shape=list(shape))


def _batch_size_like(ref, shape, input_dim_idx, output_dim_idx):
    shape = list(shape)
    shape[output_dim_idx] = unwrap(ref).shape[input_dim_idx]
    return shape


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,  # noqa: A002
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return gaussian_random(_batch_size_like(input, shape, input_dim_idx,
                                            output_dim_idx), mean, std,
                           seed, dtype)


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    from ..tensor.random import uniform
    return uniform(list(shape), dtype=dtype, min=min, max=max, seed=seed)


def uniform_random_batch_size_like(input, shape, dtype="float32",  # noqa: A002
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    return uniform_random(_batch_size_like(input, shape, input_dim_idx,
                                           output_dim_idx), dtype, min,
                          max, seed)


def hash(input, hash_size, num_hash=1, name=None):  # noqa: A002
    """Multiplicative integer hashing into [0, hash_size) with num_hash
    lanes (reference hash_op uses xxhash; the CONTRACT — deterministic
    bucketing of int ids — is preserved, the exact hash family is not,
    as documented)."""
    base = [2654435761, 2246822519, 3266489917, 668265263, 374761393,
            2654435789, 2246822579, 3266489989]
    # extend deterministically past 8 lanes (odd multipliers stay odd)
    mults = [base[i] if i < len(base)
             else (base[i % len(base)] + 2 * (i // len(base)) * 104729)
             for i in range(num_hash)]
    primes = jnp.asarray(mults, jnp.uint32)

    def raw(v):
        v = v.astype(jnp.uint32)
        out = (v[..., None, :] * primes[:, None]) % jnp.uint32(hash_size)
        return out.astype(jnp.int64)

    return dispatch("hash", raw, input)


def image_resize_short(input, out_short_len, resample="BILINEAR"):  # noqa: A002
    from ..nn.functional import interpolate
    h, w = unwrap(input).shape[2:]
    short = min(h, w)
    scale = out_short_len / short
    return interpolate(input, size=[int(round(h * scale)),
                                    int(round(w * scale))],
                       mode=resample.lower())


def resize_linear(input, out_shape=None, scale=None, name=None,  # noqa: A002
                  actual_shape=None, align_corners=True, align_mode=1,
                  data_format="NCW"):
    from ..nn.functional import interpolate
    return interpolate(input, size=out_shape, scale_factor=scale,
                       mode="linear", align_corners=align_corners,
                       data_format=data_format)


def inplace_abn(input, act=None, **bn_kwargs):  # noqa: A002
    """Activated batch norm (reference inplace_abn_op) — XLA fuses the
    activation into the norm; 'inplace' is a memory-pass concern the
    donation system owns."""
    raise UnimplementedError(
        "inplace_abn: use nn.BatchNorm2D + the activation directly — "
        "XLA fuses them; there is no separate in-place pass to request")


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    from ..nn.functional import normalize
    return normalize(x, p=2, axis=axis, epsilon=epsilon)


def lod_append(x, level):
    """LoD is subsumed by masked-dense batches — appending a level is a
    no-op on the dense values (documented passthrough)."""
    return x


def lod_reset(x, y=None, target_lod=None):
    """See lod_append: segmentation travels as explicit lengths in this
    repo, the dense values are unchanged."""
    return x


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,  # noqa: A002
        data_format="NCHW"):
    """Local response normalization (reference lrn_op)."""
    def raw(x):
        ch_axis = 1 if data_format.startswith("NC") else -1
        xt = jnp.moveaxis(x, ch_axis, 1)
        sq = jnp.square(xt)
        c = xt.shape[1]
        half = n // 2
        pad = jnp.pad(sq, [(0, 0), (half, n - 1 - half)] +
                      [(0, 0)] * (xt.ndim - 2))
        acc = sum(pad[:, i:i + c] for i in range(n))
        out = xt / jnp.power(k + alpha * acc, beta)
        return jnp.moveaxis(out, 1, ch_axis)

    return dispatch("lrn", raw, input)


def merge_selected_rows(x, name=None):
    """Merge duplicate rows of a RowSparseGrad (reference
    merge_selected_rows_op over scatter::MergeAdd)."""
    from ..core.selected_rows import RowSparseGrad
    from ..optimizer.sparse import merge_rows
    if not isinstance(x, RowSparseGrad):
        return x
    rows, vals = merge_rows(x.rows, x.values, x.dense_shape[0])
    return RowSparseGrad(rows, vals, x.dense_shape)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    """mul_op: flatten both sides to 2-D then matmul."""
    def raw(xv, yv):
        xm = xv.reshape((int(np.prod(xv.shape[:x_num_col_dims])), -1))
        ym = yv.reshape((int(np.prod(yv.shape[:y_num_col_dims])), -1))
        return xm @ ym

    return dispatch("mul", raw, x, y)


def py_func(func, x, out=None, backward_func=None, skip_vars_in_backward_input=None):
    """Eager python call (reference py_func_op).  Tracing cannot call back
    into python, so this is the EAGER path only."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


def random_crop(x, shape, seed=None):
    """Random spatial crop to `shape` (reference random_crop_op) —
    host-side offset draw, device slice."""
    xv = unwrap(x)
    shape = list(shape)
    nd = len(shape)
    rng = np.random.RandomState(seed)
    starts = [0] * (xv.ndim - nd) + [
        int(rng.randint(0, xv.shape[xv.ndim - nd + i] - shape[i] + 1))
        for i in range(nd)]
    sizes = list(xv.shape[:xv.ndim - nd]) + shape

    def raw(v):
        return jax.lax.dynamic_slice(v, starts, sizes)

    return dispatch("random_crop", raw, x)


def reduce_all(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    def raw(v):
        ax = tuple(dim) if isinstance(dim, (list, tuple)) else dim
        return jnp.all(v, axis=ax, keepdims=keep_dim)
    return dispatch("reduce_all", raw, input)


def reduce_any(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    def raw(v):
        ax = tuple(dim) if isinstance(dim, (list, tuple)) else dim
        return jnp.any(v, axis=ax, keepdims=keep_dim)
    return dispatch("reduce_any", raw, input)


def row_conv(input, future_context_size, weight=None, act=None,  # noqa: A002
             param_attr=None):
    """Functional row_conv (reference row_conv_op); weight
    (future_context_size + 1, D) explicit — the stateful form is
    legacy_layers.RowConv."""
    if weight is None:
        raise InvalidArgumentError(
            "row_conv: pass `weight` explicitly or use "
            "nn.legacy_layers.RowConv")

    def raw(xv, wv):
        t = xv.shape[1]
        ctx = wv.shape[0]
        pad = jnp.pad(xv, [(0, 0), (0, ctx - 1), (0, 0)])
        out = sum(pad[:, i:i + t] * wv[i] for i in range(ctx))
        return out

    out = dispatch("row_conv", raw, input, weight)
    from ..nn.legacy_layers import _apply_act
    return _apply_act(out, act)


def similarity_focus(input, axis, indexes, name=None):  # noqa: A002
    """Similarity-focus mask (reference similarity_focus_op.h): for each
    selected channel (via `indexes` on `axis`), sort the 2-D plane over
    the other two dims descending and GREEDILY pick positions whose row
    and column are both still untagged — min(A, B) mutually-exclusive
    positions per channel — then set 1 at those positions across the whole
    `axis` dim (union over the selected channels).  Host-side numpy: the
    greedy sequential assignment is an eager-compat op, not a hot path."""
    xv = unwrap(input)
    if isinstance(xv, jax.core.Tracer):
        raise UnimplementedError(
            "similarity_focus is eager-only (greedy sequential assignment "
            "runs host-side) — call it outside jit/TrainStep")
    xv = np.asarray(jax.device_get(xv))
    if xv.ndim != 4:
        raise InvalidArgumentError(
            f"similarity_focus: input must be 4-D, got {xv.ndim}-D")
    if axis not in (1, 2, 3):
        raise InvalidArgumentError(
            f"similarity_focus: axis must be 1, 2 or 3, got {axis}")
    out = np.zeros_like(xv)
    other = [d for d in (1, 2, 3) if d != axis]
    a_dim, b_dim = xv.shape[other[0]], xv.shape[other[1]]
    limit = min(a_dim, b_dim)
    for i in range(xv.shape[0]):
        for idx in indexes:
            if not 0 <= idx < xv.shape[axis]:
                raise InvalidArgumentError(
                    f"similarity_focus: index {idx} out of range for "
                    f"axis {axis} (size {xv.shape[axis]})")
            plane = np.take(xv[i], idx, axis=axis - 1)      # (A, B)
            order = np.argsort(-plane, axis=None, kind="stable")
            tag_a = np.zeros(a_dim, bool)
            tag_b = np.zeros(b_dim, bool)
            picked = 0
            for pos in order:
                ia, ib = divmod(int(pos), b_dim)
                if tag_a[ia] or tag_b[ib]:
                    continue
                tag_a[ia] = tag_b[ib] = True
                picked += 1
                sel = [i, slice(None), slice(None), slice(None)]
                sel[other[0]], sel[other[1]] = ia, ib
                out[tuple(sel)] = 1
                if picked == limit:
                    break
    return Tensor(jnp.asarray(out), stop_gradient=True)


def size(input, name=None):  # noqa: A002
    from ..tensor.attribute import numel
    return numel(input)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Functional spectral norm (reference spectral_norm_op): weight
    divided by its leading singular value via power iteration (fresh u
    each call — the stateful form is nn.SpectralNorm)."""
    def raw(wv):
        w = jnp.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
        u = jnp.ones((w.shape[0],), w.dtype)
        for _ in range(max(power_iters, 1)):
            v = w.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = w @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ (w @ v)
        return wv / jnp.maximum(sigma, eps)

    return dispatch("spectral_norm", raw, weight)


def unique_with_counts(x, dtype="int32"):
    """Eager-only (dynamic output shape): unique values, reconstruction
    index, counts."""
    xv = np.asarray(jax.device_get(unwrap(x))).reshape(-1)
    out, index, counts = np.unique(xv, return_inverse=True,
                                   return_counts=True)
    return (Tensor(jnp.asarray(out), stop_gradient=True),
            Tensor(jnp.asarray(index.astype(dtype)), stop_gradient=True),
            Tensor(jnp.asarray(counts.astype(dtype)), stop_gradient=True))


# --- control flow ---------------------------------------------------------


def Assert(cond, data=None, summarize=20, name=None):  # noqa: N802
    cv = np.asarray(jax.device_get(unwrap(cond)))
    if not bool(np.all(cv)):
        from ..core.errors import PreconditionNotMetError
        payload = [np.asarray(jax.device_get(unwrap(d)))[:summarize]
                   for d in (data or [])]
        raise PreconditionNotMetError(
            f"[Assert] condition is false; data={payload}")
    return cond


def Print(input, first_n=-1, message=None, summarize=20,  # noqa: N802,A002
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    v = np.asarray(jax.device_get(unwrap(input)))
    print(f"{message or 'Var'}: shape={v.shape} dtype={v.dtype} "
          f"values={v.reshape(-1)[:summarize]}")
    return input


class While:
    """Program-region While (reference control_flow.While).  A python
    `with` body cannot be conditionally skipped, so the faithful eager
    form does not exist — use static.nn.while_loop (lax.while_loop) or a
    plain python loop."""

    def __init__(self, *a, **k):
        raise UnimplementedError(
            "While: use fluid.layers.while_loop / static.nn.while_loop "
            "(lax) or a python loop — program block regions do not exist "
            "here")


class Switch:
    """See While: use static.nn.case / python if-chains."""

    def __init__(self, *a, **k):
        raise UnimplementedError(
            "Switch: use fluid.layers.case / python conditionals — "
            "program block regions do not exist here")


class IfElse:
    """See While: use static.nn.cond or boolean masking."""

    def __init__(self, *a, **k):
        raise UnimplementedError(
            "IfElse: use fluid.layers.cond or jnp.where masking — "
            "program block regions do not exist here")


class DynamicRNN:
    """Era-compat dynamic RNN builder.  The masked-dense world runs
    sequence models with nn.RNN / legacy dynamic_lstm-style scans; this
    class would re-introduce per-timestep LoD shrinking, so it raises
    with the modern recipe instead of silently mis-running."""

    def __init__(self, *a, **k):
        raise UnimplementedError(
            "DynamicRNN: use nn.RNN(cell)(inputs, sequence_length=...) or "
            "fluid.layers.dynamic_lstm/dynamic_gru over masked-dense "
            "batches — LoD program regions do not exist here")


def reorder_lod_tensor_by_rank(x, rank_table):
    raise UnimplementedError(
        "reorder_lod_tensor_by_rank: masked-dense batches need no length "
        "reordering — feed sequence_length to the RNN layers instead")


# --- losses ---------------------------------------------------------------


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None):
    """Levenshtein distance per pair (reference edit_distance_op),
    host-side numpy (serving/eval metric)."""
    a = np.asarray(jax.device_get(unwrap(input)))
    b = np.asarray(jax.device_get(unwrap(label)))
    if a.ndim == 1:
        a, b = a[None], b[None]
    al = (np.asarray(jax.device_get(unwrap(input_length))).reshape(-1)
          if input_length is not None
          else np.full(len(a), a.shape[1]))
    bl = (np.asarray(jax.device_get(unwrap(label_length))).reshape(-1)
          if label_length is not None
          else np.full(len(b), b.shape[1]))
    ignored = set(ignored_tokens or [])
    out = np.zeros((len(a), 1), np.float32)
    seq_num = len(a)
    for i in range(seq_num):
        s1 = [t for t in a[i][:al[i]].tolist() if t not in ignored]
        s2 = [t for t in b[i][:bl[i]].tolist() if t not in ignored]
        d = np.arange(len(s2) + 1, dtype=np.float64)
        for j, c1 in enumerate(s1, 1):
            prev = d.copy()
            d[0] = j
            for k, c2 in enumerate(s2, 1):
                d[k] = min(prev[k] + 1, d[k - 1] + 1,
                           prev[k - 1] + (c1 != c2))
        dist = d[-1] if len(s1) else len(s2)
        out[i, 0] = dist / max(len(s2), 1) if normalized else dist
    return (Tensor(jnp.asarray(out), stop_gradient=True),
            Tensor(jnp.asarray([seq_num], jnp.int64), stop_gradient=True))


def huber_loss(input, label, delta):  # noqa: A002
    def raw(x, y):
        d = jnp.abs(x - y)
        return jnp.where(d <= delta, 0.5 * d * d,
                         delta * (d - 0.5 * delta))
    return dispatch("huber_loss", raw, input, label)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    def raw(lab, l, r):
        return jnp.maximum(0.0, -lab * (l - r) + margin)
    return dispatch("margin_rank_loss", raw, label, left, right)


def rank_loss(label, left, right, name=None):
    def raw(lab, l, r):
        return jnp.log1p(jnp.exp(l - r)) - lab * (l - r)
    return dispatch("rank_loss", raw, label, left, right)


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits
                                       =True, use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """Sampled softmax CE (reference sampled_softmax...op): CE over the
    true class + uniformly sampled negatives instead of the full vocab.
    seed=0 (the reference's "nondeterministic" sentinel) draws FRESH
    negatives each call via core.rng.next_key() — the dropout pattern, so
    inside a TrainStep trace the draw rides the per-step traced key
    instead of being baked in as a constant, and paddle.seed() keeps
    eager runs reproducible.  A nonzero seed pins a single call exactly."""
    lv = unwrap(logits)
    lab = unwrap(label).reshape(-1).astype(jnp.int32)
    n, v = lv.shape
    if seed:
        host_rng = np.random.RandomState(seed)
        neg = jnp.asarray(host_rng.randint(0, v, (num_samples,)), jnp.int32)
    else:
        from ..core import rng as _core_rng
        neg = jax.random.randint(_core_rng.next_key(), (num_samples,),
                                 0, v, dtype=jnp.int32)

    def raw(lg):
        cols = jnp.concatenate([lab[:, None], jnp.broadcast_to(
            neg, (n, num_samples))], axis=1)          # (N, 1+S)
        picked = jnp.take_along_axis(lg, cols, axis=1)
        if remove_accidental_hits:
            hit = cols[:, 1:] == lab[:, None]
            picked = picked.at[:, 1:].set(
                jnp.where(hit, -1e20, picked[:, 1:]))
        lse = jax.nn.logsumexp(picked.astype(jnp.float32), axis=1)
        return (lse - picked[:, 0].astype(jnp.float32)).reshape(-1, 1)

    return dispatch("sampled_softmax_ce", raw, logits)


def warpctc(input, label, blank=0, norm_by_times=False,  # noqa: A002
            input_length=None, label_length=None):
    from ..nn.functional import ctc_loss
    return ctc_loss(input, label, input_length, label_length, blank=blank,
                    reduction="none")


# --- sequence (masked-dense forms) ---------------------------------------


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,  # noqa: A002
                  padding=True, padding_start=None, weight=None, bias=None,
                  act=None, **_ignored):
    """Context-window conv over time (reference sequence_conv_op):
    weight (filter_size * D, num_filters) explicit."""
    if weight is None:
        raise InvalidArgumentError(
            "sequence_conv: pass `weight` ((filter_size*D, num_filters)) "
            "explicitly — see nn.functional.fc for the convention")
    start = (-(filter_size // 2) if padding_start is None
             else padding_start)

    def raw(xv, wv, bv):
        b, t, d = xv.shape
        cols = []
        for i in range(filter_size):
            ofs = start + i
            if ofs < 0:
                sl = jnp.pad(xv[:, :t + ofs], [(0, 0), (-ofs, 0), (0, 0)])
            else:
                sl = jnp.pad(xv[:, ofs:], [(0, 0), (0, ofs), (0, 0)])
            cols.append(sl)
        im2col = jnp.concatenate(cols, axis=-1)       # (B, T, fs*D)
        out = im2col @ wv
        if bv is not None:
            out = out + bv.reshape(1, 1, -1)
        return out

    out = dispatch("sequence_conv", raw, input, weight, bias)
    from ..nn.legacy_layers import _apply_act
    return _apply_act(out, act)


def sequence_expand(x, y, ref_level=-1, name=None, lengths=None):
    """Row-expand x by per-row repeat counts (reference
    sequence_expand_op).  Masked-dense form: `lengths` (or y's row count
    pattern) gives the repeat count per x row."""
    if lengths is None:
        raise InvalidArgumentError(
            "sequence_expand: pass `lengths` (repeats per row) — the LoD "
            "of y does not travel with dense tensors")
    reps = np.asarray(jax.device_get(unwrap(lengths))).reshape(-1)
    idx = jnp.asarray(np.repeat(np.arange(len(reps)), reps), jnp.int32)
    return dispatch("sequence_expand", lambda v: v[idx], x)


def sequence_reshape(input, new_dim):  # noqa: A002
    def raw(v):
        return v.reshape(-1, new_dim)
    return dispatch("sequence_reshape", raw, input)


def sequence_scatter(input, index, updates, name=None):  # noqa: A002
    def raw(x, i, u):
        return x.at[i.astype(jnp.int32)].add(u)
    return dispatch("sequence_scatter", raw, input, index, updates)


def sequence_slice(input, offset, length, name=None):  # noqa: A002
    """Per-sequence slice (reference sequence_slice_op) on (B, T, ...)
    masked-dense batches."""
    def raw(x, off, ln):
        t = x.shape[1]
        pos = jnp.arange(t)[None, :]
        keep = (pos >= off.reshape(-1, 1)) & \
            (pos < (off + ln).reshape(-1, 1))
        # left-align each kept span
        order = jnp.argsort(~keep, axis=1, stable=True)
        gathered = jnp.take_along_axis(
            x, order[..., None] if x.ndim == 3 else order, axis=1)
        maxlen = int(jnp.max(ln)) if not isinstance(
            ln, jax.core.Tracer) else t
        gathered = gathered[:, :maxlen]
        pos = jnp.arange(gathered.shape[1])[None, :]
        mask = pos < ln.reshape(-1, 1)          # zero PAST each row's length
        if x.ndim == 3:
            mask = mask[..., None]
        return gathered * mask

    return dispatch("sequence_slice", raw, input, offset, length)


# --- tensor builders ------------------------------------------------------


def create_tensor(dtype, name=None, persistable=False):
    from ..core.dtype import convert_dtype
    return Tensor(jnp.zeros((0,), convert_dtype(dtype)))


def fill_constant_batch_size_like(input, shape, dtype, value,  # noqa: A002
                                  input_dim_idx=0, output_dim_idx=0,
                                  force_cpu=False):
    from ..compat import fill_constant
    return fill_constant(_batch_size_like(input, shape, input_dim_idx,
                                          output_dim_idx), dtype, value)


def tensor_array_to_tensor(input, axis=1, name=None,  # noqa: A002
                           use_stack=False):
    from ..tensor.manipulation import concat, stack
    arrs = list(input)
    out = stack(arrs, axis=axis) if use_stack else concat(arrs, axis=axis)
    sizes = [unwrap(a).shape[axis] if not use_stack else 1 for a in arrs]
    return out, Tensor(jnp.asarray(sizes, jnp.int32), stop_gradient=True)


# --- io shims -------------------------------------------------------------


def double_buffer(reader, place=None, name=None):
    """Prefetch is the DataLoader's job here (io/dataloader.py device
    prefetch) — passthrough."""
    return reader


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    raise UnimplementedError(
        "py_reader: use paddle.io.DataLoader (worker processes + device "
        "prefetch) — feed-queue program readers do not exist here")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    raise UnimplementedError(
        "create_py_reader_by_data: use paddle.io.DataLoader")


def read_file(reader):
    raise UnimplementedError(
        "read_file: file readers are python iterables here — iterate the "
        "DataLoader directly")


def load(out, file_path, load_as_fp16=None):
    from ..framework import load as _load
    state = _load(file_path, return_numpy=True)
    if hasattr(out, "_set_data"):
        first = state if not isinstance(state, dict) else \
            next(iter(state.values()))
        out._set_data(jnp.asarray(first))
    return out
