"""paddle.fluid alias package — wholesale `from paddle import fluid` ports.

Reference: python/paddle/fluid/__init__.py.  Pure wiring (documented as
such): every name resolves to its 2.0-native home in this repo — tracing
replaces Programs, masked-dense tensors replace LoD — so era code keeps
its spelling while running the TPU-native path.
"""
from __future__ import annotations

# executor / program machinery (static shims)
from ..static import (  # noqa: F401
    Program, Executor, CompiledProgram, ParallelExecutor, BuildStrategy,
    ExecutionStrategy, Scope, Variable, default_main_program,
    default_startup_program, program_guard, name_scope, global_scope,
    scope_guard, cpu_places, cuda_places, append_backward, gradients,
    load_program_state, set_program_state, save, load,
)
from ..compat import (  # noqa: F401
    data, create_global_var, fill_constant, LoDTensor, LoDTensorArray,
    get_tensor_from_selected_rows,
)
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace, is_compiled_with_cuda,
    device_count as core_device_count,
)
from ..nn.layer_base import ParamAttr  # noqa: F401
from ..core.tensor import Tensor  # noqa: F401
from ..utils.checkpoint import save as save_dygraph, load as load_dygraph  # noqa: F401

from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import io  # noqa: F401
from . import optimizer  # noqa: F401
from . import core  # noqa: F401
# era submodules with the fluid-era spellings (Xavier/MSRA factories,
# *Regularizer/*Initializer aliases, set_gradient_clip, the numpy
# metric accumulators)
from . import initializer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import metrics  # noqa: F401

# fluid.embedding / one_hot live at the package top level too
from .layers import embedding, one_hot  # noqa: F401


def install_check():  # fluid.install_check.run_check analogue
    from ..utils import run_check
    run_check()
