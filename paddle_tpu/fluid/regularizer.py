"""fluid.regularizer alias module (reference: python/paddle/fluid/
regularizer.py __all__ = L1Decay, L2Decay, L1DecayRegularizer,
L2DecayRegularizer)."""
from ..regularizer import L1Decay, L2Decay  # noqa: F401

L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer",
           "L2DecayRegularizer"]
