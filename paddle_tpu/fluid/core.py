"""fluid.core alias module (reference: paddle/fluid/pybind — the C++
binding surface).  The handles era code touches resolve to their Python
homes; there is no separate binding layer to expose (SURVEY: pybind is
subsumed by running on jax)."""
from __future__ import annotations

from ..compat import (  # noqa: F401
    LoDTensor, LoDTensorArray, get_tensor_from_selected_rows,
)
from ..core.device import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, XPUPlace,
)
from ..core.selected_rows import RowSparseGrad as SelectedRows  # noqa: F401
from ..core.errors import EnforceNotMet  # noqa: F401


def get_cuda_device_count():
    from ..core.device import device_count
    return device_count()


def is_compiled_with_cuda():
    from ..core.device import is_compiled_with_cuda as f
    return f()
