"""fluid.dygraph alias module (reference: python/paddle/fluid/dygraph/).
Eager IS the execution model here, so guard() is a no-op context and
to_variable is to_tensor."""
from __future__ import annotations

import contextlib

from ..nn.layer_base import Layer, ParamAttr  # noqa: F401
from ..nn import (  # noqa: F401
    Linear, Conv2D, BatchNorm, Embedding, Dropout, LayerNorm, GRUCell,
    LSTMCell, Sequential, LayerList, ParameterList,
)
from ..nn.legacy_layers import Pool2D, NCELoss as NCE  # noqa: F401
from ..core.tensor import Tensor  # noqa: F401
from ..tensor.creation import to_tensor as to_variable  # noqa: F401
from ..utils.checkpoint import (  # noqa: F401
    save as save_dygraph, load as load_dygraph,
)
from ..distributed.parallel_layer import DataParallel  # noqa: F401
from ..jit import to_static as jit_to_static  # noqa: F401
from ..jit import TracedLayer  # noqa: F401


@contextlib.contextmanager
def guard(place=None):
    """fluid.dygraph.guard — eager is always on; accepted for compat."""
    yield


def enabled():
    return True


no_grad = __import__("paddle_tpu.core.tensor", fromlist=["no_grad"]).no_grad
