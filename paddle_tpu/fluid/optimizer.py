"""fluid.optimizer alias module (reference:
python/paddle/fluid/optimizer.py) — the *Optimizer spellings over the 2.0
optimizer classes.  fluid's `learning_rate` positional convention matches
the 2.0 classes here, so aliasing is exact."""
from __future__ import annotations

from ..optimizer import (  # noqa: F401
    SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Adadelta, Lamb,
    LarsMomentum,
)

SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
RMSPropOptimizer = RMSProp
AdadeltaOptimizer = Adadelta
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
