"""fluid.optimizer alias module (reference:
python/paddle/fluid/optimizer.py) — the *Optimizer spellings over the 2.0
optimizer classes.  fluid's `learning_rate` positional convention matches
the 2.0 classes here, so aliasing is exact."""
from __future__ import annotations

from ..optimizer import (  # noqa: F401
    SGD, Momentum, Adagrad, Adam, AdamW, Adamax, RMSProp, Adadelta, Lamb,
    LarsMomentum,
)

SGDOptimizer = SGD
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
RMSPropOptimizer = RMSProp
AdadeltaOptimizer = Adadelta
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
from ..optimizer import (  # noqa: F401,E402
    Ftrl, DecayedAdagrad, Dpsgd, Lookahead as LookaheadOptimizer,
    ExponentialMovingAverage, ModelAverage,
)

FtrlOptimizer = Ftrl
DecayedAdagradOptimizer = DecayedAdagrad
DpsgdOptimizer = Dpsgd


class RecomputeOptimizer:
    """Era wrapper (reference fluid/optimizer.py RecomputeOptimizer):
    marks checkpoint segments for activation recompute.  TPU-native: the
    compiled path is `jit.TrainStep(..., remat=True)` / the fleet
    recompute meta-optimizer (jax.checkpoint); eagerly, minimize is
    semantically identical (recompute only trades memory)."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def __getattr__(self, name):
        # minimize/step/clear_grad all delegate to the inner optimizer —
        # the base Optimizer.minimize already returns the era
        # (optimize_ops, params_grads) pair
        return getattr(self._optimizer, name)


class PipelineOptimizer:
    """Era wrapper (reference fluid/optimizer.py PipelineOptimizer): tags
    the program for pipeline execution.  TPU-native: the executing path is
    `parallel.pipeline.gpt_pipeline_step` / ShardedTrainStep over a pp
    mesh axis; this wrapper keeps the era construction site importable and
    delegates the optimizer surface."""

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches

    def __getattr__(self, name):
        # delegates minimize/step/clear_grad (base contract included)
        return getattr(self._optimizer, name)
