"""fluid.layers alias module (reference: python/paddle/fluid/layers/) —
the era-typical flat namespace, re-exported from the 2.0-native homes:
tensor ops from `tensor`, nn functionals from `nn.functional`, detection
from `vision.ops`, dynamic RNN from `nn.legacy_rnn`, control flow +
TensorArray verbs from `static.nn`.  Wiring only; see each target for the
implementation and its reference citation."""
from __future__ import annotations

# --- tensor / math (2.0 names are a superset of the fluid ones) ---------
from ..tensor import *  # noqa: F401,F403
from ..compat import (  # noqa: F401
    reduce_max, reduce_min, reduce_mean, reduce_prod, reduce_sum,
    elementwise_floordiv, elementwise_mod, elementwise_pow, fill_constant,
    create_global_var, data, tensordot, has_inf, has_nan, crop_tensor,
)
from ..tensor.math import (  # noqa: F401
    add as elementwise_add, subtract as elementwise_sub,
    multiply as elementwise_mul, divide as elementwise_div,
    maximum as elementwise_max, minimum as elementwise_min,
)

# --- nn functionals (activations, fc, pooling, losses, sequence) --------
from ..nn.functional import *  # noqa: F401,F403
from ..nn.functional import (  # noqa: F401
    fc, pool2d, pool3d, pad2d, smooth_l1, softmax_with_cross_entropy,
    sequence_pad, sequence_unpad, sequence_pool, sequence_softmax,
    sequence_reverse, sequence_concat, sequence_enumerate,
    sequence_expand_as, linear_chain_crf, crf_decoding,
)
from ..nn.functional.loss import (  # noqa: F401
    binary_cross_entropy_with_logits as sigmoid_cross_entropy_with_logits,
)

# --- embeddings ----------------------------------------------------------
from ..nn.functional.common import embedding  # noqa: F401
from ..nn.functional.common import one_hot  # noqa: F401

# --- dynamic RNN + units (masked-dense LoD answer) ----------------------
from ..nn.legacy_rnn import (  # noqa: F401
    dynamic_lstm, dynamic_lstmp, dynamic_gru, gru_unit, lstm_unit,
)
from ..nn.legacy_layers import (  # noqa: F401
    StaticRNN, ctc_greedy_decoder, clip_by_norm, nce, data_norm,
    affine_channel, center_loss, im2sequence,
)

# --- control flow + TensorArray -----------------------------------------
from ..static.nn import (  # noqa: F401
    while_loop, cond, case, switch_case, create_array, array_write,
    array_read, array_length,
)

# --- detection family (vision.ops is the 2.0 home) ----------------------
from ..vision.ops import (  # noqa: F401
    prior_box, density_prior_box, anchor_generator, box_coder,
    iou_similarity, box_clip, bipartite_match, target_assign, ssd_loss,
    detection_output, multiclass_nms, yolo_box, roi_align, roi_pool,
    psroi_pool, prroi_pool, deformable_roi_pooling, generate_proposals,
    distribute_fpn_proposals, collect_fpn_proposals,
)
from ..vision.ops import yolo_loss as yolov3_loss  # noqa: F401
from ..vision.ops import matrix_nms  # noqa: F401
from ..vision.rcnn_ops import (  # noqa: F401
    rpn_target_assign, retinanet_target_assign, generate_proposal_labels,
    generate_mask_labels, retinanet_detection_output, locality_aware_nms,
    box_decoder_and_assign, roi_perspective_transform,
    polygon_box_transform,
)

# --- seq2seq decode family (nn.decode is the 2.0 home) ------------------
from ..nn.decode import (  # noqa: F401
    Decoder, BeamSearchDecoder, DecodeHelper, TrainingHelper,
    GreedyEmbeddingHelper, SampleEmbeddingHelper, BasicDecoder,
    dynamic_decode, beam_search, beam_search_decode, gather_tree,
)
from ..nn.layer.rnn import (  # noqa: F401
    RNNCellBase as RNNCell, GRUCell, LSTMCell,
)


from .layers_extra import *  # noqa: F401,F403,E402  (nn/control_flow/loss/
#                              sequence/tensor/io long tail)
# kept OUT of layers_extra so its internal loops keep the builtin range
from ..tensor.creation import arange as range  # noqa: F401,E402,A004

# --- metrics (reference fluid/layers/metric_op.py __all__) --------------
from ..metric import accuracy, auc  # noqa: F401,E402

# --- LR decay functional family (reference learning_rate_scheduler.py) --
from . import learning_rate_scheduler  # noqa: F401,E402
from .learning_rate_scheduler import (  # noqa: F401,E402
    exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, noam_decay, cosine_decay,
    linear_lr_warmup,
)


def hard_shrink(x, threshold=None):
    """fluid.layers.hard_shrink (reference fluid/layers/ops.py:449, a
    generate_layer_fn over hard_shrink_op; threshold defaults to 0.5)."""
    from ..nn.functional import hardshrink
    return hardshrink(x, 0.5 if threshold is None else threshold)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   head=None, **kwargs):
    """fluid.layers.multi_box_head: stateful conv heads cannot be built by
    a traced function (no LayerHelper param store) — construct a
    `paddle.vision.models.MultiBoxHead` once and pass it as `head`, or use
    it directly as a Layer."""
    from ..core.errors import InvalidArgumentError
    if head is None:
        raise InvalidArgumentError(
            "multi_box_head: pass `head=MultiBoxHead(...)` (see "
            "paddle.vision.models.MultiBoxHead) — the repo's fluid "
            "convention for LayerHelper-created parameters")
    return head(inputs, image)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """fluid.layers.rnn (reference rnn.py:520): run `cell` over the time
    axis — the nn.RNN layer is the 2.0 home; this wraps it with fluid's
    argument order."""
    from ..nn.layer.rnn import RNN
    runner = RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return runner(inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """fluid.layers.birnn (reference rnn.py:660) over nn.BiRNN."""
    from ..nn.layer.rnn import BiRNN
    runner = BiRNN(cell_fw, cell_bw, time_major=time_major)
    return runner(inputs,
                  None if initial_states is None else tuple(initial_states),
                  sequence_length)


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,  # noqa: A002
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1, layer=None):
    """fluid.layers.lstm — the cudnn-style stacked LSTM (reference
    rnn.py:2426).  Stateful weights cannot be created by a traced
    function (no LayerHelper param store): build `paddle.nn.LSTM(...)`
    once and pass it as `layer`, the repo's fluid convention (see
    nn.functional.fc)."""
    from ..core.errors import InvalidArgumentError
    if layer is None:
        raise InvalidArgumentError(
            "fluid.layers.lstm: pass `layer=paddle.nn.LSTM(input_size, "
            "hidden_size, num_layers, direction=...)` — LayerHelper "
            "param creation does not exist here")
    out, (h, c) = layer(input, (init_h, init_c))
    return out, h, c
