"""Text dataset readers: Conll05st, WMT14, WMT16, Movielens.

Reference: python/paddle/text/datasets/{conll05,wmt14,wmt16,movielens}.py —
same archive formats and per-item shapes, local-files-only (this
environment has zero egress, so `data_file` paths are required; there is
no download path).
"""
from __future__ import annotations

import gzip
import re
import tarfile
import zipfile
from collections import defaultdict

import numpy as np

from ..io.dataset import Dataset

__all__ = ["Conll05st", "WMT14", "WMT16", "Movielens"]

_UNK_IDX = 2  # wmt convention: <s>=0, <e>=1, <unk>=2
_START, _END, _UNK = "<s>", "<e>", "<unk>"


def _lines(fileobj):
    for line in fileobj:
        yield line.decode("utf-8", "ignore") if isinstance(line, bytes) \
            else line


class Conll05st(Dataset):
    """CoNLL-2005 SRL test split (reference: conll05.py — the public data
    is the WSJ test section; items are the 9-column feature tuple the
    reference emits: words, 5 predicate-context columns, predicate, mark,
    label ids)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="test"):
        for arg, nm in ((data_file, "data_file"),
                        (word_dict_file, "word_dict_file"),
                        (verb_dict_file, "verb_dict_file"),
                        (target_dict_file, "target_dict_file")):
            if arg is None:
                raise ValueError(f"Conll05st requires {nm} (no downloads)")
        self.word_dict = self._read_dict(word_dict_file)
        self.predicate_dict = self._read_dict(verb_dict_file)
        self.label_dict = self._read_label_dict(target_dict_file)
        self._load(data_file)

    @staticmethod
    def _read_dict(path):
        with open(path) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    @staticmethod
    def _read_label_dict(path):
        tags = set()
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line[:2] in ("B-", "I-"):
                    tags.add(line[2:])
        d = {}
        for tag in sorted(tags):  # sorted: id mapping must be stable
            d[f"B-{tag}"] = len(d)  # across processes (hash-seed-free)
            d[f"I-{tag}"] = len(d)
        d["O"] = len(d)
        return d

    def _load(self, data_file):
        self.sentences, self.predicates, self.labels = [], [], []
        with tarfile.open(data_file, "r:*") as tf:
            wf = tf.extractfile(
                "conll05st-release/test.wsj/words/test.wsj.words.gz")
            pf = tf.extractfile(
                "conll05st-release/test.wsj/props/test.wsj.props.gz")
            with gzip.GzipFile(fileobj=wf) as words, \
                    gzip.GzipFile(fileobj=pf) as props:
                sent, cols = [], []
                for wline, pline in zip(_lines(words), _lines(props)):
                    w = wline.strip()
                    p = pline.strip().split()
                    if not p:  # sentence boundary
                        self._emit(sent, cols)
                        sent, cols = [], []
                    else:
                        sent.append(w)
                        cols.append(p)
                self._emit(sent, cols)

    def _emit(self, sent, cols):
        if not cols:
            return
        ncol = len(cols[0])
        verbs = [row[0] for row in cols if row[0] != "-"]
        for ci in range(1, ncol):
            tags, cur, inside = [], "O", False
            for row in cols:
                tok = row[ci]
                if tok == "*":
                    tags.append(f"I-{cur}" if inside else "O")
                elif tok == "*)":
                    tags.append(f"I-{cur}")
                    inside = False
                elif "(" in tok:
                    cur = tok[1:tok.find("*")]
                    tags.append(f"B-{cur}")
                    inside = ")" not in tok
                else:
                    raise RuntimeError(f"unexpected props token {tok!r}")
            self.sentences.append(list(sent))
            self.predicates.append(verbs[ci - 1])
            self.labels.append(tags)

    def __len__(self):
        return len(self.sentences)

    def __getitem__(self, idx):
        sent = self.sentences[idx]
        labels = self.labels[idx]
        n = len(sent)
        v = labels.index("B-V")
        mark = np.zeros(n, np.int64)
        ctx = {}
        for off, name in ((-2, "n2"), (-1, "n1"), (0, "c0"), (1, "p1"),
                          (2, "p2")):
            j = v + off
            if 0 <= j < n:
                mark[j] = 1
                ctx[name] = sent[j]
            else:
                ctx[name] = "bos" if off < 0 else "eos"
        wd = self.word_dict
        word_idx = np.array([wd.get(w, _UNK_IDX) for w in sent], np.int64)

        def rep(word):
            return np.full(n, wd.get(word, _UNK_IDX), np.int64)
        pred = np.full(n, self.predicate_dict.get(self.predicates[idx],
                                                  _UNK_IDX), np.int64)
        lab = np.array([self.label_dict[t] for t in labels], np.int64)
        return (word_idx, rep(ctx["n2"]), rep(ctx["n1"]), rep(ctx["c0"]),
                rep(ctx["p1"]), rep(ctx["p2"]), pred, mark, lab)

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict


class WMT14(Dataset):
    """WMT'14 en->fr (reference: wmt14.py — tar with src.dict/trg.dict and
    {mode}/{mode} tab-separated parallel text; items are
    (src_ids, trg_ids, trg_ids_next))."""

    def __init__(self, data_file=None, mode="train", dict_size=-1):
        if data_file is None:
            raise ValueError("WMT14 requires data_file (no downloads)")
        if mode not in ("train", "test", "gen"):
            raise ValueError(mode)
        assert dict_size > 0, "dict_size should be a positive number"
        self.dict_size = dict_size
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file, "r:*") as tf:
            self.src_dict = self._dict_from(tf, "src.dict")
            self.trg_dict = self._dict_from(tf, "trg.dict")
            data_names = [m.name for m in tf.getmembers()
                          if m.name.endswith(f"{mode}/{mode}")]
            for name in data_names:
                for line in _lines(tf.extractfile(name)):
                    parts = line.strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src = [self.src_dict.get(w, _UNK_IDX)
                           for w in [_START] + parts[0].split() + [_END]]
                    trg = [self.trg_dict.get(w, _UNK_IDX)
                           for w in parts[1].split()]
                    if len(src) > 80 or len(trg) > 80:
                        continue
                    self.src_ids.append(src)
                    self.trg_ids.append([self.trg_dict[_START]] + trg)
                    self.trg_ids_next.append(trg + [self.trg_dict[_END]])

    def _dict_from(self, tf, suffix):
        names = [m.name for m in tf.getmembers() if m.name.endswith(suffix)]
        assert len(names) == 1, f"expected one {suffix} in archive"
        d = {}
        for i, line in enumerate(_lines(tf.extractfile(names[0]))):
            if i >= self.dict_size:
                break
            d[line.strip()] = i
        for i, w in enumerate((_START, _END, _UNK)):
            d[w] = i
        return d

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def get_dict(self, reverse=False):
        if reverse:
            return ({v: k for k, v in self.src_dict.items()},
                    {v: k for k, v in self.trg_dict.items()})
        return self.src_dict, self.trg_dict


class WMT16(Dataset):
    """WMT'16 en<->de (reference: wmt16.py — tar with wmt16/{train,val,
    test} tab-separated text; vocab built from the train split on first
    use; items are (src_ids, trg_ids, trg_ids_next))."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en"):
        if data_file is None:
            raise ValueError("WMT16 requires data_file (no downloads)")
        if mode not in ("train", "test", "val"):
            raise ValueError(mode)
        assert src_dict_size > 0 and trg_dict_size > 0
        self.lang = lang
        self.data_file = data_file
        # one pass over the train split counts BOTH columns' vocabularies
        # (per-dict scans would decompress the archive twice more)
        self.src_dict, self.trg_dict = self._build_dicts(
            src_dict_size, trg_dict_size, lang)
        start, end, unk = (self.src_dict[_START], self.src_dict[_END],
                           self.src_dict[_UNK])
        src_col = 0 if lang == "en" else 1
        self.src_ids, self.trg_ids, self.trg_ids_next = [], [], []
        with tarfile.open(data_file, "r:*") as tf:
            for line in _lines(tf.extractfile(f"wmt16/{mode}")):
                parts = line.strip().split("\t")
                if len(parts) != 2:
                    continue
                src = [start] + [self.src_dict.get(w, unk)
                                 for w in parts[src_col].split()] + [end]
                trg = [self.trg_dict.get(w, unk)
                       for w in parts[1 - src_col].split()]
                self.src_ids.append(src)
                self.trg_ids.append([start] + trg)
                self.trg_ids_next.append(trg + [end])

    def _build_dicts(self, src_size, trg_size, lang):
        src_col = 0 if lang == "en" else 1
        freqs = (defaultdict(int), defaultdict(int))
        with tarfile.open(self.data_file, "r:*") as tf:
            for line in _lines(tf.extractfile("wmt16/train")):
                parts = line.strip().split("\t")
                if len(parts) == 2:
                    for col in (0, 1):
                        for w in parts[col].split():
                            freqs[col][w] += 1

        def build(freq, size):
            words = sorted(freq, key=lambda w: (-freq[w], w))
            d = {w: i for i, w in enumerate((_START, _END, _UNK))}
            for w in words[:max(size - 3, 0)]:
                d[w] = len(d)
            return d
        return (build(freqs[src_col], src_size),
                build(freqs[1 - src_col], trg_size))

    def __len__(self):
        return len(self.src_ids)

    def __getitem__(self, idx):
        return (np.array(self.src_ids[idx]), np.array(self.trg_ids[idx]),
                np.array(self.trg_ids_next[idx]))

    def get_dict(self, lang="en", reverse=False):
        d = self.src_dict if lang == self.lang else self.trg_dict
        return {v: k for k, v in d.items()} if reverse else d


# era age bucketing (reference movielens.py age_table)
_ML_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    """Era record for one movie (reference movielens.py:37): id, category
    names and title; value() resolves them through the vocab dicts."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, movie_title_dict):
        return [[self.index],
                [categories_dict[c] for c in self.categories],
                [movie_title_dict[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    """Era record for one user (reference movielens.py:62): id, gender
    flag, bucketed age index and job id."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = _ML_AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [[self.index], [0 if self.is_male else 1], [self.age],
                [self.job_id]]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender({self.is_male}), "
                f"age({self.age}), job({self.job_id})>")


class Movielens(Dataset):
    """MovieLens-1M ratings (reference: movielens.py — ml-1m zip with
    movies.dat/users.dat/ratings.dat '::'-separated; items are
    (user_id, gender, age, job, movie_id, category_ids, title_ids,
    rating))."""

    _TITLE_RE = re.compile(r"^(.*)\((\d+)\)$")

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        if data_file is None:
            raise ValueError("Movielens requires data_file (no downloads)")
        if mode not in ("train", "test"):
            raise ValueError(mode)
        self.categories_dict = {}
        self.movie_title_dict = {}
        movies, users = {}, {}
        with zipfile.ZipFile(data_file) as z:
            with z.open("ml-1m/movies.dat") as f:
                for line in _lines(f):
                    mid, title, cats = line.strip().split("::")
                    m = self._TITLE_RE.match(title)
                    title_words = (m.group(1) if m else title).lower().split()
                    for c in cats.split("|"):
                        self.categories_dict.setdefault(
                            c, len(self.categories_dict))
                    for w in title_words:
                        self.movie_title_dict.setdefault(
                            w, len(self.movie_title_dict))
                    movies[int(mid)] = (
                        [self.categories_dict[c] for c in cats.split("|")],
                        [self.movie_title_dict[w] for w in title_words])
            with z.open("ml-1m/users.dat") as f:
                for line in _lines(f):
                    uid, gender, age, job, _ = line.strip().split("::")
                    users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                       int(job))
            rng = np.random.RandomState(rand_seed)
            self.data = []
            with z.open("ml-1m/ratings.dat") as f:
                for line in _lines(f):
                    uid, mid, rating, _ = line.strip().split("::")
                    uid, mid = int(uid), int(mid)
                    if mid not in movies or uid not in users:
                        continue
                    is_test = rng.rand() < test_ratio
                    if (mode == "test") != is_test:
                        continue
                    g, a, j = users[uid]
                    cats, title = movies[mid]
                    self.data.append((uid, g, a, j, mid, cats, title,
                                      float(rating)))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        uid, g, a, j, mid, cats, title, rating = self.data[idx]
        return (np.array(uid), np.array(g), np.array(a), np.array(j),
                np.array(mid), np.array(cats), np.array(title),
                np.array(rating, np.float32))
