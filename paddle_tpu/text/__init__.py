"""paddle_tpu.text (datasets/models) — built out."""
