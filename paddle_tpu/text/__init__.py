"""paddle_tpu.text (reference: python/paddle/text/ — dataset readers:
Imdb, Imikolov, Movielens, UCIHousing, WMT14/16, Conll05).

Zero-egress: readers parse the standard local archives; `FakeTextDataset`
provides synthetic LM data for tests/benchmarks.
"""
from __future__ import annotations

import gzip
import io
import re
import tarfile

import numpy as np

from ..io.dataset import Dataset

from .packing import pack_sequences, BucketByLengthBatchSampler  # noqa: F401
from .datasets import (Conll05st, WMT14, WMT16, Movielens,  # noqa: F401
                       MovieInfo, UserInfo)

__all__ = ["FakeTextDataset", "Imdb", "Imikolov", "UCIHousing",
           "ViterbiDecoder", "viterbi_decode", "pack_sequences",
           "BucketByLengthBatchSampler", "Conll05st", "WMT14", "WMT16",
           "Movielens"]


class FakeTextDataset(Dataset):
    """Deterministic synthetic token-id LM dataset."""

    def __init__(self, num_samples=256, seq_len=128, vocab_size=1024, seed=0):
        self.num_samples = num_samples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self._seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + int(idx))
        ids = rng.randint(0, self.vocab_size,
                          (self.seq_len,)).astype(np.int32)
        return ids[:-1], ids[1:].astype(np.int64)


def _tokenize(text):
    return re.findall(r"[a-z]+", text.lower())


class Imdb(Dataset):
    """reference: text/datasets/imdb.py — reads the aclImdb tar archive."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        if data_file is None:
            raise ValueError("Imdb requires data_file (no downloads here)")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels, freq = [], [], {}
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                match = pat.match(m.name)
                if not match:
                    continue
                toks = _tokenize(tf.extractfile(m).read().decode(
                    "utf-8", "ignore"))
                docs.append(toks)
                labels.append(0 if match.group(1) == "pos" else 1)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        vocab = [w for w, c in sorted(freq.items(),
                                      key=lambda x: (-x[1], x[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.asarray([self.word_idx.get(t, unk) for t in d],
                                np.int64) for d in docs]
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]


class Imikolov(Dataset):
    """reference: text/datasets/imikolov.py — PTB-style n-gram dataset."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        if data_file is None:
            raise ValueError("Imikolov requires data_file")
        name = ("./simple-examples/data/ptb.train.txt" if mode == "train"
                else "./simple-examples/data/ptb.valid.txt")
        freq, lines = {}, []
        with tarfile.open(data_file, "r:*") as tf:
            f = tf.extractfile(name)
            for line in io.TextIOWrapper(f, encoding="utf-8"):
                toks = line.split()
                lines.append(toks)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
        vocab = sorted(w for w, c in freq.items()
                       if c >= min_word_freq and w != "<unk>")
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.samples = []
        for toks in lines:
            ids = [self.word_idx.get(t, unk)
                   for t in ["<s>"] + toks + ["<e>"]]
            if data_type.upper() == "NGRAM":
                for i in range(window_size, len(ids) + 1):
                    self.samples.append(
                        np.asarray(ids[i - window_size:i], np.int64))
            else:
                self.samples.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        s = self.samples[idx]
        return s[:-1], s[-1:]


class UCIHousing(Dataset):
    """reference: text/datasets/uci_housing.py — whitespace table, 14 cols."""

    def __init__(self, data_file=None, mode="train"):
        if data_file is None:
            raise ValueError("UCIHousing requires data_file")
        op = gzip.open if data_file.endswith(".gz") else open
        with op(data_file, "rt") as f:
            rows = [list(map(float, line.split()))
                    for line in f if line.strip()]
        data = np.asarray(rows, np.float32)
        feats, target = data[:, :-1], data[:, -1:]
        # normalize features like the reference (max/min/avg per column)
        mx, mn, avg = feats.max(0), feats.min(0), feats.mean(0)
        feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
        split = int(len(data) * 0.8)
        if mode == "train":
            self.feats, self.target = feats[:split], target[:split]
        else:
            self.feats, self.target = feats[split:], target[split:]

    def __len__(self):
        return len(self.feats)

    def __getitem__(self, idx):
        return self.feats[idx], self.target[idx]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True):
    """Viterbi decoding (reference: paddle.text.ViterbiDecoder /
    operators/viterbi_decode) — lax.scan over time, jittable on TPU."""
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor, unwrap

    pots = unwrap(potentials)          # (B, T, N)
    trans = unwrap(transition_params)  # (N, N)

    def step(score, emit):
        cand = score[:, :, None] + trans[None]   # (B, N_prev, N)
        best = cand.max(axis=1) + emit
        idx = cand.argmax(axis=1)
        return best, idx

    init = pots[:, 0]
    scores, backptrs = jax.lax.scan(step, init,
                                    jnp.swapaxes(pots[:, 1:], 0, 1))
    last_tag = scores.argmax(-1)       # (B,)

    def backtrack(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    _, path = jax.lax.scan(backtrack, last_tag, backptrs, reverse=True)
    path = jnp.concatenate([jnp.swapaxes(path, 0, 1),
                            last_tag[:, None]], axis=1)
    return Tensor(scores.max(-1)), Tensor(path.astype(jnp.int64))


class ViterbiDecoder:
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
