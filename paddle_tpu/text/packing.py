"""Packed-sequence batching — the TPU-native LoD batching story.

Reference: LoDTensor batches many variable-length sequences as one packed
buffer + offset table (paddle/fluid/framework/lod_tensor.h:114).  On TPU
the same density win comes from packing several sequences into each fixed
row and masking attention with SEGMENT IDS, which the pallas flash kernel
applies in-kernel (ops/flash_attention.py q/kv_segment_ids) — no (S, S)
mask tensor is ever materialized.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

__all__ = ["pack_sequences", "BucketByLengthBatchSampler"]


def pack_sequences(seqs: Sequence[np.ndarray], row_len: int,
                   pad_id: int = 0, truncate: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy first-fit packing of 1-D token sequences into fixed rows.

    A sequence longer than row_len raises unless truncate=True (silent
    token loss would misalign labels derived from the original sequence).

    Returns (tokens, segment_ids, positions), each (rows, row_len) int32:
    - tokens: packed ids, pad_id in the slack
    - segment_ids: 1-based id per packed sequence, 0 on padding — feed to
      flash attention's q/kv_segment_ids so tokens attend only within
      their own sequence (padding id 0 never matches a real segment...
      except other padding; give each row's padding its own unique id 0
      and mask pad positions out of the loss instead)
    - positions: position within each original sequence (0 on padding) —
      the position-embedding index for packed rows
    """
    rows: List[List[np.ndarray]] = []
    space: List[int] = []
    for s in seqs:
        s = np.asarray(s)
        if s.ndim != 1:
            raise ValueError("pack_sequences packs 1-D token sequences")
        if len(s) > row_len:
            if not truncate:
                raise ValueError(
                    f"sequence of length {len(s)} exceeds row_len "
                    f"{row_len}; pass truncate=True to clip it")
            s = s[:row_len]
        placed = False
        for i, free in enumerate(space):
            if len(s) <= free:
                rows[i].append(s)
                space[i] -= len(s)
                placed = True
                break
        if not placed:
            rows.append([s])
            space.append(row_len - len(s))

    n = len(rows)
    tokens = np.full((n, row_len), pad_id, np.int32)
    segs = np.zeros((n, row_len), np.int32)
    pos = np.zeros((n, row_len), np.int32)
    for i, row in enumerate(rows):
        off = 0
        for j, s in enumerate(row):
            tokens[i, off:off + len(s)] = s
            segs[i, off:off + len(s)] = j + 1
            pos[i, off:off + len(s)] = np.arange(len(s))
            off += len(s)
    return tokens, segs, pos


class BucketByLengthBatchSampler:
    """Batch sampler grouping examples of similar length to minimize pad
    waste (reference: the LoD batching path + fluid.layers batch-by-size
    readers; torch's BucketBatchSampler is the common analogue).

    lengths: per-example sequence lengths.
    bucket_boundaries: ascending cut points; example with length L goes to
    the first bucket with L <= boundary (overflow bucket at the end).
    """

    def __init__(self, lengths, bucket_boundaries, batch_size,
                 shuffle=False, drop_last=False, seed=0):
        self.lengths = np.asarray(lengths)
        self.boundaries = sorted(bucket_boundaries)
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._rng = np.random.RandomState(seed)

    def _bucket_of(self, n):
        for i, b in enumerate(self.boundaries):
            if n <= b:
                return i
        return len(self.boundaries)

    def _batches(self):
        buckets: List[List[int]] = [[] for _ in
                                    range(len(self.boundaries) + 1)]
        order = np.arange(len(self.lengths))
        if self.shuffle:
            self._rng.shuffle(order)
        out = []
        for idx in order:
            b = buckets[self._bucket_of(self.lengths[idx])]
            b.append(int(idx))
            if len(b) == self.batch_size:
                out.append(list(b))
                b.clear()
        for b in buckets:
            if b and not self.drop_last:
                out.append(list(b))
        if self.shuffle:
            self._rng.shuffle(out)
        return out

    def __iter__(self):
        return iter(self._batches())

    def __len__(self):
        # count WITHOUT touching the RNG: bucket membership is a pure
        # function of lengths, so the batch count doesn't depend on the
        # shuffle order (len() advancing the RNG would make epoch order
        # depend on how many times a progress bar called len())
        counts = [0] * (len(self.boundaries) + 1)
        for n in self.lengths:
            counts[self._bucket_of(n)] += 1
        total = 0
        for c in counts:
            total += c // self.batch_size
            if c % self.batch_size and not self.drop_last:
                total += 1
        return total
