"""Deduped gather / per-shard scatter numerics for giant embedding tables.

Reference lineage: the parameter-server sparse path —
operators/distributed/parameter_prefetch.cc (deduplicate lookup ids, pull
only the live rows), operators/math/selected_rows_functor.cc MergeAdd, and
adam_op.h lazy_mode.  TPU-native: every shape is static under jit, so the
dedup keeps full lookup-count buffers with out-of-range sentinels (the
`optimizer.sparse.merge_rows` convention) and the per-shard update reuses
`lazy_row_update` INSIDE a shard_map — each mesh shard touches only its own
rows, no densify, no all-gather of the table.

Bit-exactness contract (tests/test_embedding_shard.py): the deduped gather
returns exactly `w[ids]`, and the per-shard lazy update is bit-identical to
the single-device `lazy_row_update` — merge order per row id is preserved
because rebasing ids by the shard offset is monotone and jnp.argsort is
stable, so segment sums add the same values in the same order.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.selected_rows import RowSparseGrad
from ..core.tensor import Tensor, unwrap


def dedup_ids(flat_ids, height: int):
    """Static-shape dedup of (n,) lookup ids.

    Returns (uids, inv, n_unique): uids (n,) int32 holds the unique ids in
    the leading slots and the sentinel `height` in the rest; inv (n,) int32
    maps each lookup position to its unique slot (never a sentinel slot);
    n_unique is a traced scalar.  out = w[uids][inv] == w[flat_ids] exactly.
    """
    n = flat_ids.shape[0]
    ids = flat_ids.astype(jnp.int32)
    order = jnp.argsort(ids)  # stable: duplicate ids keep original order
    sr = ids[order]
    if n > 1:
        is_new = jnp.concatenate(
            [jnp.ones((1,), bool), sr[1:] != sr[:-1]])
    else:
        is_new = jnp.ones((n,), bool)
    seg = jnp.cumsum(is_new) - 1
    inv = jnp.zeros((n,), jnp.int32).at[order].set(seg.astype(jnp.int32))
    uids = jax.ops.segment_max(sr, seg, num_segments=n)
    n_unique = seg[-1] + 1
    uids = jnp.where(jnp.arange(n) < n_unique, uids, height)
    return uids.astype(jnp.int32), inv, n_unique


def dedup_gather(w, flat_ids):
    """Gather w[flat_ids] touching each live row once: dedup, gather the
    unique rows, re-expand.  Returns (out (n, width), uids, inv)."""
    height = w.shape[0]
    uids, inv, _ = dedup_ids(flat_ids, height)
    rows = jnp.take(w, jnp.clip(uids, 0, height - 1), axis=0)
    return jnp.take(rows, inv, axis=0), uids, inv


def psum_gather(w, uids, axis: str, mesh):
    """Row-sharded gather: each shard gathers the uids it owns, zeroes the
    rest, and a psum over `axis` assembles the (n, width) result — the
    cross-shard traffic is O(unique rows · width), never the table.

    Shards other than the owner contribute exact zeros, so the psum is
    bit-identical to a single-device gather."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    height = w.shape[0]
    local_h = height // mesh.shape[axis]

    def local(w_l, uids):
        start = jax.lax.axis_index(axis) * local_h
        lids = uids - start
        mine = (lids >= 0) & (lids < local_h)
        rows = jnp.take(w_l, jnp.clip(lids, 0, local_h - 1), axis=0)
        rows = jnp.where(mine[:, None], rows, jnp.zeros((), rows.dtype))
        return jax.lax.psum(rows, axis)

    return shard_map(local, mesh=mesh,
                     in_specs=(P(axis, None), P()), out_specs=P(),
                     check_rep=False)(w, uids)


def _state_specs(state, height: int, axis: str):
    """PartitionSpec tree for an optimizer-state dict: row leaves (leading
    dim == table height) shard with the table, scalars replicate."""
    from jax.sharding import PartitionSpec as P

    def spec(s):
        if (hasattr(s, "shape") and getattr(s, "ndim", 0) >= 1
                and s.shape[0] == height):
            return P(*(((axis,) + (None,) * (s.ndim - 1))))
        return P()
    return jax.tree_util.tree_map(spec, state)


def sharded_lazy_row_update(optimizer, p, grad: RowSparseGrad, state, lr,
                            step_no, axis: str, mesh,
                            decay_flag: bool = True, lr_mult: float = 1.0):
    """Per-shard lazy row update for a row-sharded table: each shard rebases
    the global lookup ids into its own row range (foreign ids become the
    local sentinel) and runs the SAME `lazy_row_update` on its local shard —
    O(lookups·width) work per shard, writes strictly local, moments of
    untouched rows untouched.  The distributed half of adam_op.h lazy_mode,
    with GSPMD placement instead of a parameter server."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from ..optimizer.sparse import lazy_row_update

    height, width = p.shape
    nshards = mesh.shape[axis]
    local_h = height // nshards
    st_specs = _state_specs(state, height, axis)

    def local(p_l, state_l, rows, values, lr, step_no):
        start = jax.lax.axis_index(axis) * local_h
        lids = rows - start
        mine = (lids >= 0) & (lids < local_h)
        # foreign lookups get the local sentinel: merge_rows groups them
        # into segments whose scatter-back is dropped (mode="drop")
        lids = jnp.where(mine, lids, local_h).astype(jnp.int32)
        g = RowSparseGrad(lids, values, (local_h, width))
        return lazy_row_update(optimizer, p_l, g, state_l, lr, step_no,
                               decay_flag, lr_mult)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(axis, None), st_specs, P(), P(), P(), P()),
        out_specs=(P(axis, None), st_specs),
        check_rep=False)(p, state, grad.rows, grad.values, lr, step_no)


# ---------------------------------------------------------------------------
# lookup entry point (eager + TrainStep sparse-grad channel)
# ---------------------------------------------------------------------------

def _note_lookup_stats(flat_ids):
    """Host-side dedup counters (concrete ids only — traced lookups are
    counted by the host-table pipeline instead)."""
    try:
        ids = np.asarray(flat_ids)
    except Exception:
        return
    from ..utils.monitor import stat_add
    stat_add("STAT_embedding_rows_gathered", int(ids.size))
    stat_add("STAT_embedding_rows_unique", int(np.unique(ids).size))


def ctx_sharded_lookup(ctx, x, weight, padding_idx=None):
    """ShardedEmbedding lookup inside a TrainStep trace: the deduped
    (optionally psum-sharded) gather runs under stop_gradient and the
    per-lookup gradient rides the zeros-cotangent channel, exactly like
    `selected_rows.ctx_embedding` — so the step's RowSparseGrad is
    bit-identical to the plain Embedding(sparse=True) path."""
    ids = unwrap(x).astype(jnp.int32)
    w = unwrap(weight)
    name = getattr(weight, "name", None) or "sharded_embedding"
    key = ctx.key_for(name)
    width = w.shape[1]
    height = w.shape[0]
    n = int(np.prod(ids.shape))

    if ctx.mode == "record":
        ctx.specs[key] = (n, width, w.dtype)
        out = jnp.take(w, ids, axis=0)
    else:
        z = ctx.zeros[key]
        flat = ids.reshape(-1)
        ctx.ids[key] = flat
        uids, inv, _ = dedup_ids(flat, height)
        axis = getattr(weight, "row_shard_axis", None)
        mesh = getattr(weight, "row_shard_mesh", None)
        wsg = jax.lax.stop_gradient(w)
        if axis is not None and mesh is not None and mesh.shape[axis] > 1:
            rows = psum_gather(wsg, jnp.clip(uids, 0, height - 1),
                               axis, mesh)
        else:
            rows = jnp.take(wsg, jnp.clip(uids, 0, height - 1), axis=0)
        out = (jnp.take(rows, inv, axis=0).reshape(ids.shape + (width,))
               + z.reshape(ids.shape + (width,)))
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None],
                        jnp.zeros((), out.dtype), out)
    return Tensor(out, stop_gradient=True)


def sharded_lookup(x, weight, padding_idx=None):
    """F.embedding analogue for ShardedEmbedding weights: routes through the
    TrainStep sparse-grad context when one is active, else the eager
    tape path (RowSparseGrad cotangent), else a plain deduped gather."""
    from ..core import selected_rows as sr
    from ..core.tensor import is_grad_enabled

    ctx = sr.current_ctx()
    name = getattr(weight, "name", None) or "sharded_embedding"
    if ctx is not None:
        if ctx.wants(name):
            return ctx_sharded_lookup(ctx, x, weight, padding_idx)
        # demoted (tied) weight: fall through to the dense differentiable
        # path via F.embedding below
        from ..nn import functional as F
        return F.embedding(x, weight, padding_idx=padding_idx, sparse=False)
    mesh = getattr(weight, "row_shard_mesh", None)
    if mesh is not None and not isinstance(unwrap(x), jax.core.Tracer):
        # eager on a mesh: ids must live on the table's device set before
        # mixing with the row-sharded weight (replicated — they're small)
        from jax.sharding import NamedSharding, PartitionSpec as P
        x = Tensor(jax.device_put(unwrap(x), NamedSharding(mesh, P())))
    ids = unwrap(x)
    if not isinstance(ids, jax.core.Tracer):
        _note_lookup_stats(ids.reshape(-1))
    if (isinstance(weight, Tensor) and is_grad_enabled()
            and not weight.stop_gradient):
        return sr.eager_sparse_embedding(x, weight, padding_idx)
    out, _, _ = dedup_gather(unwrap(weight), ids.reshape(-1).astype(jnp.int32))
    out = out.reshape(tuple(ids.shape) + (weight.shape[1],))
    if padding_idx is not None:
        out = jnp.where((unwrap(x) == padding_idx)[..., None],
                        jnp.zeros((), out.dtype), out)
    return Tensor(out, stop_gradient=True)
