"""ShardedEmbedding — a mesh row-sharded device embedding table.

Reference: distributed_lookup_table_op + the Fleet sparse table split across
parameter-server shards (SURVEY.md L2/L8).  TPU-native: the "servers" are
mesh shards — the weight lives row-sharded over one mesh axis
(parallel.sharding.row_spec), the forward gather dedups lookup ids and
psum-assembles only the live rows, and the sparse gradient feeds the lazy
row-wise optimizer update PER SHARD (embedding.functional
.sharded_lazy_row_update): no densify, no all-gather of the table, writes
strictly local to each shard.
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding

from ..core.errors import enforce
from ..nn.layer_base import Layer
from ..nn import initializer as I
from . import functional as EF


class ShardedEmbedding(Layer):
    """Embedding whose (num_embeddings, embedding_dim) weight is row-sharded
    over `axis` of `mesh` (default: the global mesh's "tp" axis).

    Gradients always flow as RowSparseGrad through the sparse channel
    (sparse=True semantics — the whole point of the layer); the same
    restriction applies: the weight must only be consumed via this lookup.
    With no mesh (or axis size 1) the layer degrades to a single-shard
    deduped-gather embedding, so model code is mesh-agnostic.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 mesh=None, axis: str = "tp", padding_idx: Optional[int] = None,
                 weight_attr=None, name=None):
        super().__init__()
        if mesh is None:
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = (None if padding_idx is None else
                            padding_idx if padding_idx >= 0 else
                            num_embeddings + padding_idx)
        self.mesh = mesh
        self.axis = axis
        nshards = mesh.shape.get(axis, 1) if mesh is not None else 1
        enforce(num_embeddings % max(1, nshards) == 0,
                f"ShardedEmbedding: num_embeddings {num_embeddings} must "
                f"divide evenly over mesh axis {axis!r} (size {nshards})")
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 1.0))
        self.weight.sparse_grad = True
        if nshards > 1:
            from ..parallel.sharding import row_spec
            self.weight.row_shard_axis = axis
            self.weight.row_shard_mesh = mesh
            self.weight._set_data(jax.device_put(
                self.weight._data, NamedSharding(mesh, row_spec(axis))))
        if self.padding_idx is not None:
            self.weight._set_data(
                self.weight._data.at[self.padding_idx].set(0.0))

    def forward(self, x):
        return EF.sharded_lookup(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        nshards = (self.mesh.shape.get(self.axis, 1)
                   if self.mesh is not None else 1)
        return (f"{self.num_embeddings}, {self.embedding_dim}, "
                f"axis={self.axis!r}, shards={nshards}")
