"""Host-RAM-resident embedding tables bigger than device memory.

Reference: the FleetWrapper parameter-server pull/push sparse cycle
(fleet_wrapper.cc PullSparseVarsSync / PushSparseVarsWithLabelAsync): the
trainer pulls only the current batch's deduped rows from the PS, trains on
the pulled slab, and pushes the updated rows back.  TPU-native, the "PS" is
host RAM on the same machine: the table (param rows + row-wise optimizer
moments) lives as numpy arrays, and a double-buffered prefetch pipeline
pulls the NEXT batch's deduped rows to device while the current compiled
step runs, then writes the updated rows back — the dataloader-prefetch /
async-checkpoint overlap discipline applied to parameters themselves.

Correctness contract (tests/test_embedding_shard.py):
- async prefetch is BIT-IDENTICAL to synchronous fetch: a prefetched slab
  that overlaps the in-flight batch's rows is re-patched from the host
  table after that batch's write-back lands (depth-1 double buffering, so
  the only possibly-stale rows are exactly that intersection);
- a poisoned fetched copy (PDTPU_FAULT_ROW_CORRUPT) is detected by the
  fetch-side finiteness verify and refetched from the host table;
- checkpoints carry table rows + optimizer moments + the data cursor, so a
  SIGKILL-interrupted run resumes bit-exact (probes/recsys_probe.py).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer


_obs_handles = None


def _obs():
    """(prefetch_wait_histogram, device_table_bytes_gauge) — created once
    (registry.reset() zeroes values in place so the cache stays valid)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.histogram("embedding_prefetch_wait_seconds",
                         "time the train loop waited for the next batch's "
                         "host-table row slab (0 ~= the prefetch fully "
                         "overlapped the step)"),
            _m.gauge("embedding_device_table_bytes",
                     "bytes of host-table rows + moments resident on "
                     "device for the current step (the working set, not "
                     "the table)"))
    return _obs_handles


class HostEmbeddingTable:
    """A (num_embeddings, embedding_dim) table in host RAM, with row-wise
    optimizer-moment slabs beside it.  Only ever touched through deduped
    row gathers/scatters — the device never holds more than one batch's
    working set."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 dtype="float32", init_scale: float = 0.01, seed: int = 0,
                 name: str = "host_table",
                 rows: Optional[np.ndarray] = None):
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.dtype = np.dtype(dtype)
        self.name = name
        if rows is not None:
            # adopt existing rows (serving wrap of a trained table): no
            # random init — at giant-table sizes the discarded f64
            # standard_normal would transiently cost 2x the table
            rows = np.asarray(rows, self.dtype)
            if rows.shape != (self.num_embeddings, self.embedding_dim):
                raise ValueError(
                    f"HostEmbeddingTable: rows shape {rows.shape} != "
                    f"({self.num_embeddings}, {self.embedding_dim})")
            self.rows = rows
        else:
            rng = np.random.RandomState(seed)
            self.rows = (rng.standard_normal(
                (num_embeddings, embedding_dim))
                * init_scale).astype(self.dtype)
        # optimizer-state slabs (e.g. adam moment1/moment2), allocated by
        # ensure_opt_state from the optimizer's own init_state template
        self.opt_slabs: Dict[str, np.ndarray] = {}

    @property
    def nbytes(self) -> int:
        return self.rows.nbytes + sum(s.nbytes for s in
                                      self.opt_slabs.values())

    def ensure_opt_state(self, optimizer):
        """Allocate the row-wise moment slabs for `optimizer` (idempotent).
        Only row-shaped state leaves are supported — exactly the ones the
        lazy row update touches."""
        if self.opt_slabs:
            return
        template = optimizer.init_state(
            jnp.zeros((1, self.embedding_dim), self.dtype))
        for k, leaf in template.items():
            if tuple(leaf.shape) != (1, self.embedding_dim):
                raise NotImplementedError(
                    f"HostEmbeddingTable: optimizer state leaf {k!r} is "
                    f"not row-wise (shape {tuple(leaf.shape)}); host "
                    "tables support row-wise-state optimizers (SGD/"
                    "Momentum/Adam family)")
            self.opt_slabs[k] = np.zeros(
                (self.num_embeddings, self.embedding_dim),
                np.dtype(str(leaf.dtype)))

    # -- row-granular access -------------------------------------------------
    def gather(self, uids: np.ndarray, cap: int
               ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
        """Copy the rows + moments for `uids` into fresh (cap, D) slabs
        (slots past len(uids) stay zero — the static-shape bucket pad)."""
        u = len(uids)
        slab = np.zeros((cap, self.embedding_dim), self.dtype)
        slab[:u] = self.rows[uids]
        states = {}
        for k, s in self.opt_slabs.items():
            st = np.zeros((cap, self.embedding_dim), s.dtype)
            st[:u] = s[uids]
            states[k] = st
        return slab, states

    def scatter(self, uids: np.ndarray, slab: np.ndarray,
                states: Dict[str, np.ndarray]):
        """Write updated rows + moments back (only the first len(uids)
        slab slots — bucket-pad rows never land)."""
        u = len(uids)
        self.rows[uids] = np.asarray(slab[:u], self.dtype)
        for k, s in states.items():
            self.opt_slabs[k][uids] = np.asarray(s[:u],
                                                 self.opt_slabs[k].dtype)

    # -- checkpoint subtree --------------------------------------------------
    def state_tree(self) -> dict:
        return {"rows": self.rows,
                "opt": {k: v for k, v in self.opt_slabs.items()}}

    def load_state_tree(self, tree: dict):
        self.rows = np.array(tree["rows"], self.dtype)
        self.opt_slabs = {k: np.array(v) for k, v in
                          tree.get("opt", {}).items()}


class PreparedBatch:
    """One batch's device-resident working set (the PS 'pulled' rows)."""

    __slots__ = ("index", "inputs", "label", "uids", "inv", "n_unique",
                 "cap", "slab", "states", "waited_s", "was_hit")

    def __init__(self, index, inputs, label, uids, inv, cap, slab, states):
        self.index = index
        self.inputs = inputs      # host arrays: model inputs before emb
        self.label = label
        self.uids = uids          # np (U,) unique global row ids
        self.inv = inv            # np (B*F,) position -> slab slot
        self.n_unique = len(uids)
        self.cap = cap            # bucket-rounded slab rows
        self.slab = slab          # host (cap, D) param rows
        self.states = states      # host {leaf: (cap, D)} moment rows
        self.waited_s = 0.0
        self.was_hit = False


def _round_bucket(n: int, bucket: int) -> int:
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


class HostPrefetchPipeline:
    """Depth-1 double-buffered row prefetch over a deterministic batch
    stream.

    `batch_fn(i)` returns batch i as (inputs..., ids, label) numpy arrays
    with ids of shape (B, F) — indexable by step so a checkpoint cursor
    can fast-forward bit-exact.  While the caller runs the compiled step
    on batch i, a worker thread is already pulling batch i+1's deduped
    rows; `complete()` pushes batch i's updated rows back and the next
    `next_prepared()` re-patches any overlap before handing the slab out.
    """

    def __init__(self, table: HostEmbeddingTable,
                 batch_fn: Callable[[int], tuple], n_batches: int,
                 optimizer=None, offsets: Optional[np.ndarray] = None,
                 async_prefetch: bool = True, bucket: int = 1024,
                 start_index: int = 0):
        self.table = table
        self.batch_fn = batch_fn
        self.n_batches = int(n_batches)
        self.offsets = (None if offsets is None
                        else np.asarray(offsets, np.int64))
        self.async_prefetch = bool(async_prefetch)
        # slab rows round up to a bucket multiple AND never shrink below
        # the run's high-water cap, so the per-batch unique count's jitter
        # (which loves to hug a bucket boundary) cannot flap the compiled
        # step's signature — a cap change is a recompile on the hot path
        self.bucket = int(bucket)
        self._cap_high_water = 0
        self._next = int(start_index)      # next batch index to consume
        self._outstanding: Optional[PreparedBatch] = None
        # (uids, rows, states) of the newest write-back — the scatter into
        # the table runs on the worker thread (off the step's critical
        # path); the overlap patch reads THESE buffers, so it never waits
        # on (or races with) the table write
        self._pending_write = None
        self._write_future = None
        self._future = None
        self._fetch_no = 0                 # 1-based, for row_corrupt
        self.hits = 0
        self.misses = 0
        self.corrupt_refetches = 0
        self.wait_seconds = 0.0
        self.peak_device_table_bytes = 0
        if optimizer is not None:
            table.ensure_opt_state(optimizer)
        self._executor = None
        if self.async_prefetch:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="paddle_tpu-emb-prefetch")
            if self._next < self.n_batches:
                self._future = self._executor.submit(self._prepare,
                                                     self._next)

    # -- worker --------------------------------------------------------------
    def _prepare(self, i: int) -> PreparedBatch:
        from ..utils import faults as _faults
        from ..utils.monitor import stat_add
        self._fetch_no += 1
        fetch_no = self._fetch_no
        _faults.maybe_stall_prefetch(fetch_no - 1)
        batch = self.batch_fn(i)
        *inputs, ids, label = batch
        ids = np.asarray(ids)
        flat = ids.reshape(-1).astype(np.int64)
        if self.offsets is not None:
            flat = (ids.astype(np.int64)
                    + self.offsets.reshape(1, -1)).reshape(-1)
        uids, inv = np.unique(flat, return_inverse=True)
        cap = max(_round_bucket(len(uids), self.bucket),
                  self._cap_high_water)
        self._cap_high_water = cap
        slab, states = self.table.gather(uids, cap)
        stat_add("STAT_embedding_rows_gathered", int(flat.size))
        stat_add("STAT_embedding_rows_unique", int(len(uids)))
        stat_add("STAT_embedding_host_to_device_bytes",
                 int(slab.nbytes + sum(s.nbytes for s in states.values())))
        if _faults.row_corrupt_fetch() == fetch_no and len(uids):
            # poison the fetched COPY (never the table): a torn transfer
            slab[0] = np.nan
        # torn-transfer verify, fetch-side so it overlaps the running step:
        # a poisoned copy is refetched from the host table (the source of
        # truth was never touched; on the worker this is serialized with
        # the scatter jobs, so it reads a consistent table)
        if len(uids) and not np.isfinite(slab[:len(uids)]).all():
            stat_add("STAT_embedding_corrupt_rows_detected")
            self.corrupt_refetches += 1
            slab[:len(uids)] = self.table.rows[uids]
        return PreparedBatch(i, tuple(np.asarray(a) for a in inputs),
                             np.asarray(label), uids,
                             inv.astype(np.int32), cap, slab, states)

    # -- consumer ------------------------------------------------------------
    def __len__(self):
        return max(0, self.n_batches - self._next)

    def next_prepared(self) -> Optional[PreparedBatch]:
        """Hand out the next batch's device-ready working set (None when
        the stream is exhausted).  The previous batch must have been
        complete()d — depth-1 double buffering is what makes the overlap
        re-patch exact."""
        from ..utils.monitor import stat_add
        if self._outstanding is not None:
            raise RuntimeError(
                "HostPrefetchPipeline: complete() the previous batch "
                "before requesting the next one")
        if self._next >= self.n_batches:
            return None
        i = self._next
        # queue the NEXT fetch first thing, so the worker picks it up the
        # moment it is free (it runs behind any queued scatter, concurrent
        # with the caller's verify/patch/stage AND the step itself)
        next_future = None
        if self._executor is not None and i + 1 < self.n_batches:
            next_future = self._executor.submit(self._prepare, i + 1)
        wait_h, bytes_g = _obs()
        t0 = time.perf_counter()
        if self._future is not None:
            hit = self._future.done()
            prep = self._future.result()
            self._future = None
        else:
            hit = False
            prep = self._prepare(i)
        prep.waited_s = time.perf_counter() - t0
        prep.was_hit = hit
        self.wait_seconds += prep.waited_s
        wait_h.observe(prep.waited_s)
        if hit:
            self.hits += 1
            stat_add("STAT_embedding_prefetch_hits")
        else:
            self.misses += 1
            stat_add("STAT_embedding_prefetch_misses")
        u = prep.n_unique
        # overlap re-patch: rows the in-flight batch just wrote back were
        # stale in the prefetched copy — pull exactly those from the
        # pending-write buffers (the table scatter may still be running on
        # the worker thread; these host copies are already final)
        if self._pending_write is not None and u:
            w_uids, w_rows, w_states = self._pending_write
            overlap = np.intersect1d(prep.uids, w_uids, assume_unique=True)
            if overlap.size:
                slots = np.searchsorted(prep.uids, overlap)
                src = np.searchsorted(w_uids, overlap)
                prep.slab[slots] = w_rows[src]
                for k, s in prep.states.items():
                    s[slots] = w_states[k][src]
        # stage onto the device; kick off the NEXT prefetch so it overlaps
        # the caller's step
        prep.slab = jnp.asarray(prep.slab)
        prep.states = {k: jnp.asarray(v) for k, v in prep.states.items()}
        prep.inv = jnp.asarray(prep.inv)
        resident = int(prep.slab.nbytes
                       + sum(s.nbytes for s in prep.states.values()))
        self.peak_device_table_bytes = max(self.peak_device_table_bytes,
                                           resident)
        bytes_g.set(resident)
        self._next = i + 1
        self._outstanding = prep
        self._future = next_future
        return prep

    def complete(self, prep: PreparedBatch, new_slab, new_states: dict):
        """Write batch `prep`'s updated rows + moments back to the host
        table (the PS 'push').  Only the device->host copy runs here; the
        table scatter itself goes to the worker thread, ORDERED after the
        already-queued next prefetch — so that prefetch reads a consistent
        pre-write table and the overlap patch supplies the new values."""
        from ..utils.monitor import stat_add
        if self._outstanding is not prep:
            raise RuntimeError("HostPrefetchPipeline: complete() got a "
                               "batch that is not the outstanding one")
        u = prep.n_unique
        rows = np.asarray(new_slab)[:u]
        states = {k: np.asarray(v)[:u] for k, v in new_states.items()}
        stat_add("STAT_embedding_device_to_host_bytes",
                 int(rows.nbytes + sum(s.nbytes for s in states.values())))
        self._pending_write = (prep.uids, rows, states)
        if self._executor is not None:
            self._write_future = self._executor.submit(
                self.table.scatter, prep.uids, rows, states)
        else:
            self.table.scatter(prep.uids, rows, states)
        self._outstanding = None

    def flush(self):
        """Block until every queued table write has landed (checkpoint
        snapshots and end-of-run reads need the table, not the pending
        buffers, to be the truth)."""
        if self._write_future is not None:
            self._write_future.result()
            self._write_future = None

    def cursor(self) -> dict:
        """Checkpoint cursor: the next batch index to consume.  Refuses
        while a batch is outstanding — its update has not reached the
        table yet, so a snapshot now would record a cursor PAST a batch
        whose rows were never written (a silently lossy resume)."""
        if self._outstanding is not None:
            raise RuntimeError(
                "HostPrefetchPipeline: cannot checkpoint with batch "
                f"{self._outstanding.index} outstanding — complete() it "
                "first so its row updates are in the table the snapshot "
                "captures")
        return {"batch_index": self._next}

    def metrics(self) -> dict:
        total = self.hits + self.misses
        return {"fetches": total, "hits": self.hits, "misses": self.misses,
                "hit_rate": (self.hits / total) if total else None,
                "wait_seconds": self.wait_seconds,
                "corrupt_refetches": self.corrupt_refetches,
                "peak_device_table_bytes": self.peak_device_table_bytes}

    def close(self):
        if self._executor is not None:
            self.flush()
            self._executor.shutdown(wait=True)
            self._executor = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_TABLE_KEY = "__host_table__"


class HostTableTrainStep:
    """One compiled step over (dense model params + the current batch's
    table working set): forward on slab[inv], backward, ONE apply_updates
    over dense params AND the slab (the slab is just another param for the
    update math — bucket-pad rows are dropped at write-back, so their
    junk moments never land).

    The model runs in 'external-embedding' mode: forward(*inputs, emb)
    where emb is the (B, F, D) gathered rows.
    """

    def __init__(self, model: Layer, loss_fn, optimizer,
                 table: HostEmbeddingTable):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.table = table
        table.ensure_opt_state(optimizer)
        self._trainable = {k for k, v in model.state_dict().items()
                           if getattr(v, "trainable", False)}
        self._sig_cache = {}
        self._opt_state = None

    def init_opt_state(self, state):
        return {k: self.optimizer.init_state(v) for k, v in state.items()
                if k in self._trainable}

    def _build(self, ids_shape):
        from ..jit import forward_loss
        from ..optimizer.functional import apply_updates, decay_flags
        opt = self.optimizer
        trainable = self._trainable
        decay = decay_flags(opt, trainable)
        decay[_TABLE_KEY] = opt._decay_applies(self.table.name)
        b, f = ids_shape
        d = self.table.embedding_dim

        def step(params, opt_state, slab, slab_state, inv, step_no, lr,
                 rng_key, batch):
            *inputs, label = batch

            def loss_of(tp, slab_v):
                full = dict(params)
                full.update(tp)
                emb = jnp.take(slab_v, inv, axis=0).reshape(b, f, d)
                loss, _outs, bufs = forward_loss(
                    self.model, self.loss_fn, full,
                    tuple(inputs) + (emb, label), rng_key,
                    return_buffer_updates=True)
                return loss, bufs

            train_params = {k: v for k, v in params.items()
                            if k in trainable}
            (loss, bufs), (grads, gslab) = jax.value_and_grad(
                loss_of, argnums=(0, 1), has_aux=True)(train_params, slab)
            from ..utils import faults as _faults
            grads = _faults.poison_grads(grads, step_no)
            all_params = dict(params)
            all_params[_TABLE_KEY] = slab
            all_grads = dict(grads)
            all_grads[_TABLE_KEY] = gslab
            all_opt = dict(opt_state)
            all_opt[_TABLE_KEY] = slab_state
            new_params, new_opt = apply_updates(
                opt, all_params, all_grads, all_opt, lr, step_no, decay)
            new_slab = new_params.pop(_TABLE_KEY)
            new_slab_state = new_opt.pop(_TABLE_KEY)
            new_params.update(bufs)
            return new_params, new_opt, loss, new_slab, new_slab_state

        from ..observability import track
        return track(f"host_table_step:{type(self.model).__name__}",
                     jax.jit(step, donate_argnums=(0, 1, 2, 3)))

    def run(self, prep: PreparedBatch, ids_shape):
        """Execute one step on a prepared batch; returns (loss, new_slab,
        new_slab_states) — hand the latter two to pipeline.complete()."""
        from ..jit import state_arrays
        from ..core import rng as _rng
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        batch = tuple(prep.inputs) + (prep.label,)
        sig = ((prep.cap,) + tuple(ids_shape)
               + tuple((tuple(np.shape(a)), str(np.asarray(a).dtype))
                       for a in batch))
        compiled = self._sig_cache.get(sig)
        if compiled is None:
            compiled = self._sig_cache[sig] = self._build(tuple(ids_shape))
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self.optimizer._step_count, jnp.int32)
        rng_key = _rng.next_key()
        new_state, self._opt_state, loss, new_slab, new_slab_state = \
            compiled(state, self._opt_state, prep.slab, prep.states,
                     prep.inv, step_no, lr, rng_key,
                     tuple(jnp.asarray(a) for a in batch))
        sd = self.model.state_dict()
        for k, v in new_state.items():
            sd[k]._set_data(v)
        return Tensor(loss), new_slab, new_slab_state

    # -- checkpointing (rows + moments + cursor: bit-exact resume) -----------
    def save_checkpoint(self, directory: str,
                        pipeline: Optional[HostPrefetchPipeline] = None,
                        step: Optional[int] = None,
                        extra_meta: Optional[dict] = None) -> str:
        from ..distributed import checkpoint as dck
        from ..jit import state_arrays
        from ..utils.monitor import stat_add
        stat_add("STAT_checkpoint_saves")
        if pipeline is not None:
            pipeline.flush()  # the table, not pending buffers, is snapshot
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = self.init_opt_state(state)
        extra = dck.train_state_extras(
            self.optimizer, extra_meta, None,
            pipeline.cursor() if pipeline is not None else None)
        tree = {"params": state, "opt": self._opt_state,
                "table": self.table.state_tree()}
        return dck.save_sharded(
            tree, directory,
            step if step is not None else self.optimizer._step_count, extra)

    def restore_checkpoint(self, directory: str) -> Optional[dict]:
        from ..distributed import checkpoint as dck
        from ..jit import state_arrays
        res = dck.restore_sharded(directory)
        if res is None:
            return None
        tree, step, extra = res
        sd = self.model.state_dict()
        for k, v in tree.get("params", {}).items():
            sd[k]._set_data(v)
        meta = dck.restore_train_extras(self.optimizer, step, extra)
        fresh = self.init_opt_state(state_arrays(self.model))
        self._opt_state = dck.merge_opt_state(fresh, tree.get("opt", {}))
        self.table.load_state_tree(tree.get("table", {}))
        return meta
