"""paddle_tpu.embedding — sharded + host-resident giant embedding tables.

The TPU-native successor to the reference's parameter-server sparse stack
(SelectedRows grads, FleetWrapper pull/push, distributed_lookup_table_op,
lazy-sparse adam): the first subsystem in this repo whose hot loop is
memory-system choreography — which rows move, when, and who owns them —
rather than matmuls.

- **functional** — deduped-index gather (`dedup_ids`/`dedup_gather`), the
  row-sharded psum gather, and the per-shard lazy row update that the
  compiled train steps call through `optimizer.functional.apply_updates`.
- **sharded**   — `ShardedEmbedding`: a device table row-sharded over a
  mesh axis (parallel.sharding.row_spec); sparse grads feed the existing
  lazy row-wise optimizer update PER SHARD — no densify, no all-gather of
  the table.
- **host_table** — `HostEmbeddingTable` (param rows + optimizer moments in
  host RAM, bigger than device memory), `HostPrefetchPipeline` (depth-1
  double-buffered async row prefetch, bit-identical to synchronous fetch),
  `HostTableTrainStep` (one compiled step over dense params + the working
  slab), with bit-exact SIGKILL resume (rows + moments + data cursor).
- **serving**   — `RecsysPredictor`: micro-batched cross-request-deduped
  scoring behind `inference.Config.enable_recsys_serving`.

See README "Recommender workload" and probes/recsys_probe.py.
"""
from .functional import (dedup_ids, dedup_gather, psum_gather,  # noqa: F401
                         sharded_lazy_row_update, sharded_lookup)
from .sharded import ShardedEmbedding  # noqa: F401
from .host_table import (HostEmbeddingTable, HostPrefetchPipeline,  # noqa: F401
                         HostTableTrainStep, PreparedBatch)
from .serving import RecsysPredictor, RecsysResponse  # noqa: F401

__all__ = [
    "dedup_ids", "dedup_gather", "psum_gather", "sharded_lazy_row_update",
    "sharded_lookup", "ShardedEmbedding", "HostEmbeddingTable",
    "HostPrefetchPipeline", "HostTableTrainStep", "PreparedBatch",
    "RecsysPredictor", "RecsysResponse",
]
