"""Serving-side recsys lookup path: micro-batched, cross-request-deduped
CTR scoring.

Reference: the PS serving path — distributed_lookup_table_op batching many
inference lookups into one pull.  Here concurrent `submit()` calls are
merged by a scorer loop (the continuous-batching discipline of
serving.ServingEngine applied to scoring): ONE dedup over the union of all
merged requests' ids, ONE host-table row fetch, ONE compiled forward.
Admission mirrors the PR-6 gateway's contract: a full queue rejects with a
typed, already-terminal response instead of raising in the caller.
`inference.Config.enable_recsys_serving(...)` routes `create_predictor`
here, so deployment looks like every other predictor.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .host_table import HostEmbeddingTable, _round_bucket


class RecsysResponse:
    """Terminal, thread-safe result handle for one scoring request."""

    def __init__(self):
        self._event = threading.Event()
        self._scores: Optional[np.ndarray] = None
        self._error: Optional[str] = None

    def _finish(self, scores=None, error=None):
        self._scores = scores
        self._error = error
        self._event.set()

    @property
    def done(self) -> bool:
        return self._event.is_set()

    @property
    def failed(self) -> bool:
        return self._event.is_set() and self._error is not None

    @property
    def error(self) -> Optional[str]:
        return self._error

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("recsys scoring request still pending")
        if self._error is not None:
            raise RuntimeError(self._error)
        return self._scores


class RecsysPredictor:
    """Batched deduped-lookup scorer over an external-embedding model.

    `model` runs in external-embedding mode: forward(dense, emb) with emb
    the gathered (B, F, D) rows; `table` holds the (giant) row store —
    a HostEmbeddingTable or a raw (rows, dim) ndarray.  `offsets` maps
    per-feature local ids into the concatenated table (DLRMConfig.offsets).
    """

    def __init__(self, model, table, offsets=None, max_batch: int = 256,
                 window_ms: float = 2.0, max_queue: int = 1024,
                 slab_bucket: int = 256, start: bool = True):
        from ..jit import functional_call, state_arrays
        if isinstance(table, np.ndarray):
            table = HostEmbeddingTable(table.shape[0], table.shape[1],
                                       dtype=table.dtype, rows=table)
        self.model = model
        self.table = table
        self.offsets = (None if offsets is None
                        else np.asarray(offsets, np.int64).reshape(1, -1))
        self.max_batch = int(max_batch)
        self.window_s = float(window_ms) / 1e3
        self.slab_bucket = int(slab_bucket)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, max_queue))
        self._state = state_arrays(model)
        self._d = table.embedding_dim
        self.requests = 0
        self.rejected = 0
        self.batches = 0
        self.scored = 0

        def pure(state, dense, inv, slab, n_feats):
            emb = jnp.take(slab, inv, axis=0).reshape(
                dense.shape[0], n_feats, self._d)
            return functional_call(model, state, dense, emb, training=False)

        from ..observability import track
        self._score = track("recsys_score",
                            jax.jit(pure, static_argnums=(4,)))
        self._closed = False
        # guards the closed-check + enqueue in submit() against close():
        # without it a submit could land AFTER close()'s drain and its
        # response would never turn terminal
        self._submit_lock = threading.Lock()
        self._thread = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="paddle_tpu-recsys-scorer",
                daemon=True)
            self._thread.start()

    # -- submission ----------------------------------------------------------
    def submit(self, dense, ids) -> RecsysResponse:
        """Enqueue one request (dense (b, dense_dim), ids (b, F)); returns
        a RecsysResponse.  A full queue or a closed predictor yields an
        already-terminal FAILED response (gateway admission semantics) —
        never an exception on the submit path."""
        resp = RecsysResponse()
        self.requests += 1
        item = (np.asarray(dense), np.asarray(ids), resp)
        with self._submit_lock:
            if self._closed:
                resp._finish(error="recsys predictor is closed")
                return resp
            try:
                self._queue.put_nowait(item)
            except queue.Full:
                self.rejected += 1
                from ..utils.monitor import stat_add
                stat_add("STAT_embedding_serving_rejects")
                resp._finish(error="recsys scoring queue full (shed)")
        return resp

    def predict(self, dense, ids, timeout: float = 30.0) -> np.ndarray:
        """Synchronous convenience: submit + wait."""
        return self.submit(dense, ids).result(timeout)

    # -- scorer loop ---------------------------------------------------------
    def _drain_window(self):
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        items = [first]
        deadline = time.perf_counter() + self.window_s
        rows = first[0].shape[0]
        while rows < self.max_batch and time.perf_counter() < deadline:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                time.sleep(self.window_s / 10)
                continue
            items.append(item)
            rows += item[0].shape[0]
        return items

    def _loop(self):
        while not self._closed:
            items = self._drain_window()
            if not items:
                continue
            try:
                self._score_batch(items)
            except Exception as e:  # terminal per-request, loop survives
                for _, _, resp in items:
                    if not resp.done:
                        resp._finish(error=f"scoring failed: "
                                           f"{type(e).__name__}: {e}")

    def _score_batch(self, items):
        from ..utils.monitor import stat_add
        dense = np.concatenate([d for d, _, _ in items], axis=0)
        ids = np.concatenate([i for _, i, _ in items], axis=0)
        if self.offsets is not None:
            ids = ids.astype(np.int64) + self.offsets
        n, f = ids.shape
        # ONE dedup across every merged request — the batched PS pull
        uids, inv = np.unique(ids.reshape(-1), return_inverse=True)
        stat_add("STAT_embedding_rows_gathered", int(ids.size))
        stat_add("STAT_embedding_rows_unique", int(uids.size))
        cap = _round_bucket(len(uids), self.slab_bucket)
        slab = np.zeros((cap, self._d), self.table.rows.dtype)
        slab[:len(uids)] = self.table.rows[uids]
        stat_add("STAT_embedding_host_to_device_bytes", int(slab.nbytes))
        # pad the merged batch to a bucket so compile count stays bounded
        bcap = _round_bucket(n, 16)
        if bcap != n:
            dense = np.concatenate(
                [dense, np.zeros((bcap - n,) + dense.shape[1:],
                                 dense.dtype)], axis=0)
            inv = np.concatenate(
                [inv, np.zeros((bcap - n) * f, inv.dtype)])
        out = self._score(self._state, jnp.asarray(dense),
                          jnp.asarray(inv.astype(np.int32)),
                          jnp.asarray(slab), f)
        scores = np.asarray(out)[:n]
        self.batches += 1
        self.scored += n
        at = 0
        for d, _, resp in items:
            b = d.shape[0]
            resp._finish(scores=scores[at:at + b])
            at += b

    # -- lifecycle -----------------------------------------------------------
    def metrics(self) -> dict:
        total = self.requests
        return {"requests": total, "rejected": self.rejected,
                "batches": self.batches, "scored": self.scored,
                "mean_merge": (self.scored / self.batches
                               if self.batches else None),
                "queue_depth": self._queue.qsize()}

    def close(self):
        with self._submit_lock:
            self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        # drain: every queued request gets a terminal response
        while True:
            try:
                _, _, resp = self._queue.get_nowait()
            except queue.Empty:
                break
            if not resp.done:
                resp._finish(error="recsys predictor closed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
