"""paddle.onnx.export equivalent.

Reference: python/paddle/onnx/export.py — a thin shim that delegates to the
external `paddle2onnx` package and errors when it is absent.  Same stance
here: the framework's own interchange format is StableHLO (`paddle.jit.save`
→ .pdmodel, the XLA-world ONNX analogue), which this function always
produces; emitting an actual .onnx protobuf additionally requires the
external `onnx` package at runtime.
"""
from __future__ import annotations

import os

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """Export `layer` for interchange.

    Always writes the StableHLO artifact (`{path}.pdmodel` + weights) via
    paddle.jit.save; when the `onnx` package is importable it ALSO writes
    `{path}.onnx` — but note that file is a single custom-domain
    ("ai.paddle_tpu") node CARRYING the StableHLO bytes, not a standard
    op-by-op ONNX graph: no stock ONNX runtime can execute it without a
    StableHLO-aware plugin.  Serve the .pdmodel with
    paddle_tpu.inference / paddle_tpu.jit.load instead.  Without `onnx`
    installed this raises ImportError after the StableHLO artifact is
    written (mirroring the reference's hard paddle2onnx dependency,
    python/paddle/onnx/export.py:1).
    """
    if input_spec is None:
        raise ValueError("onnx.export requires input_spec (static shapes)")
    from .. import jit as pjit
    base = path[:-5] if path.endswith(".onnx") else path
    pjit.save(layer, base, input_spec=input_spec)
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "onnx.export wrote the StableHLO artifact "
            f"({base}.pdmodel) but the `onnx` package is required to emit "
            "a .onnx protobuf — pip install onnx (reference parity: "
            "paddle.onnx.export requires paddle2onnx)") from e
    # With onnx available, wrap the StableHLO bytes in a single custom-op
    # ONNX graph so downstream tooling can carry the artifact.
    import numpy as np
    import onnx.helper as oh
    meta_inputs = []
    for i, s in enumerate(input_spec):
        shape = tuple(getattr(s, "shape", s[0]))
        dtype = getattr(s, "dtype", None) or s[1]
        meta_inputs.append(oh.make_tensor_value_info(
            f"x{i}", oh.np_dtype_to_tensor_dtype(np.dtype(dtype)),
            list(shape)))
    with open(base + ".pdmodel", "rb") as f:
        payload = f.read()
    node = oh.make_node("StableHLO", [vi.name for vi in meta_inputs],
                        ["out"], domain="ai.paddle_tpu",
                        module=payload)
    graph = oh.make_graph([node], "paddle_tpu", meta_inputs, [])
    model = oh.make_model(graph, opset_imports=[
        oh.make_opsetid("", opset_version),
        oh.make_opsetid("ai.paddle_tpu", 1)])
    onnx.save(model, base + ".onnx")
    return base + ".onnx"
