"""hapi callbacks (reference: python/paddle/hapi/callbacks.py —
ProgBarLogger:299, ModelCheckpoint:532, LRScheduler:595, EarlyStopping:685,
VisualDL:836, ReduceLROnPlateau:951)."""
from __future__ import annotations

import numbers
import os
import time


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get("steps")
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def _fmt(self, logs):
        items = []
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                items.append(f"{k}: {v:.4f}")
            elif hasattr(v, "__len__") and len(v) and isinstance(v[0], numbers.Number):
                items.append(f"{k}: {v[0]:.4f}")
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            print(f"step {step + 1}/{self.steps or '?'} - {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"Epoch {epoch + 1} done ({dt:.1f}s) - {self._fmt(logs)}")

    def on_eval_end(self, logs=None):
        if self.verbose:
            print(f"Eval - {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return opt._lr_scheduler if opt is not None else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.better = lambda cur, best: cur > best + self.min_delta
            self.best = -float("inf")
        else:
            self.better = lambda cur, best: cur < best - self.min_delta
            self.best = float("inf")
        self.wait = 0
        self.stopped_epoch = 0

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if hasattr(cur, "__len__"):
            cur = cur[0]
        if self.better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Metric logging to a jsonl file (VisualDL itself isn't bundled)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        rec = {"step": self._step}
        for k, v in (logs or {}).items():
            if isinstance(v, numbers.Number):
                rec[k] = float(v)
        with open(os.path.join(self.log_dir, "train.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
        self._step += 1


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        from ..optimizer.lr import ReduceOnPlateau as _R
        self.monitor = monitor
        self._inner = None
        self._kw = dict(factor=factor, patience=patience, mode="min"
                        if mode != "max" else "max", threshold=min_delta,
                        cooldown=cooldown, min_lr=min_lr, verbose=verbose)

    def on_eval_end(self, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if hasattr(cur, "__len__"):
            cur = cur[0]
        opt = self.model._optimizer
        if self._inner is None:
            from ..optimizer.lr import ReduceOnPlateau as _R
            self._inner = _R(opt.get_lr(), **self._kw)
        self._inner.step(cur)
        try:
            opt.set_lr(self._inner.last_lr)
        except RuntimeError:
            pass


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, verbose=2, log_freq=10, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs, "steps": steps,
                   "verbose": verbose, "metrics": metrics or []})
    return cl
