"""hapi Model — Keras-like fit/evaluate/predict.

Reference: python/paddle/hapi/model.py (Model.fit:808, evaluate:1296,
predict:1512) with dual Static/DynamicGraphAdapter (model.py:223,608).
TPU-native: one adapter — the jitted TrainStep (paddle_tpu.jit.TrainStep)
is the static world, eager fallback is the dygraph world, same code path.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor, no_grad, unwrap
from ..jit import TrainStep, functional_call
from ..metric import Metric
from .callbacks import config_callbacks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None
        self._amp_level = None
        self.stop_training = False

    # ---- setup -------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is not None:
            self._metrics = metrics if isinstance(metrics, (list, tuple)) else [metrics]
        if isinstance(amp_configs, str):
            self._amp_level = amp_configs if amp_configs != "O0" else None
        elif isinstance(amp_configs, dict):
            self._amp_level = amp_configs.get("level", "O1")
        self._train_step = None

    # ---- core steps ---------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        if self._train_step is None:
            loss_fn = self._loss
            self._train_step = TrainStep(
                self.network, lambda out, lbl: loss_fn(out, lbl),
                self._optimizer, amp_level=self._amp_level,
                with_outputs=bool(self._metrics))
        batch = [unwrap(Tensor(np.asarray(x)) if isinstance(x, np.ndarray) else x)
                 for x in list(inputs) + list(labels)]
        loss = self._train_step(*batch)
        metrics_out = []
        if self._metrics:
            # metrics consume the SAME forward the loss used (the reference's
            # train_batch does too) — no second forward pass; the sparse-grad
            # step threads outputs through its aux channel like the dense one
            outs = self._train_step.last_outputs
            preds = outs if len(outs) > 1 else outs[0]
            for m in self._metrics:
                m.update(unwrap(m.compute(preds, Tensor(batch[-1]))))
                metrics_out.append(m.accumulate())
        return (loss, metrics_out) if self._metrics else loss

    def eval_batch(self, inputs, labels=None):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        labels = labels if isinstance(labels, (list, tuple)) else (
            [labels] if labels is not None else [])
        self.network.eval()
        with no_grad():
            outputs = self.network(*[_as_tensor(x) for x in inputs])
            loss = None
            if self._loss is not None and labels:
                loss = self._loss(outputs, _as_tensor(labels[0]))
        metrics_out = []
        for m in self._metrics:
            m.update(unwrap(m.compute(outputs, _as_tensor(labels[0]))))
            metrics_out.append(m.accumulate())
        self.network.train()
        return loss, metrics_out

    def predict_batch(self, inputs):
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        # datasets often yield (input..., label); drop trailing extras the
        # network can't accept (reference uses the _inputs spec for this)
        import inspect
        try:
            sig = inspect.signature(self.network.forward)
            n_pos = sum(1 for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD))
            if not any(p.kind == p.VAR_POSITIONAL
                       for p in sig.parameters.values()):
                inputs = list(inputs)[:n_pos]
        except (TypeError, ValueError):
            pass
        self.network.eval()
        with no_grad():
            out = self.network(*[_as_tensor(x) for x in inputs])
        self.network.train()
        return out

    # ---- loops ---------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and not hasattr(eval_data, "__iter__"):
            eval_data = DataLoader(eval_data, batch_size=batch_size)

        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, verbose=verbose,
                                log_freq=log_freq, save_dir=save_dir,
                                save_freq=save_freq,
                                metrics=[m.name() for m in self._metrics])
        self.stop_training = False
        cbks.on_train_begin()
        it_count = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(train_loader):
                cbks.on_train_batch_begin(step)
                ins, lbls = _split_batch(batch)
                out = self.train_batch(ins, lbls)
                if isinstance(out, tuple):
                    loss, metric_vals = out
                    logs = {"loss": float(unwrap(loss))}
                    for m, v in zip(self._metrics, metric_vals):
                        logs[_mname(m)] = v
                else:
                    logs = {"loss": float(unwrap(out))}
                cbks.on_train_batch_end(step, logs)
                it_count += 1
                if num_iters is not None and it_count >= num_iters:
                    break
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self._run_eval(eval_data, cbks)
                logs.update(eval_logs)
            cbks.on_epoch_end(epoch, logs)
            if self.stop_training or (num_iters is not None and it_count >= num_iters):
                break
        cbks.on_train_end(logs)

    def _run_eval(self, eval_loader, cbks):
        cbks.on_eval_begin()
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(eval_loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = _split_batch(batch)
            loss, _ = self.eval_batch(ins, lbls)
            if loss is not None:
                losses.append(float(unwrap(loss)))
            cbks.on_eval_batch_end(step)
        logs = {}
        if losses:
            logs["eval_loss"] = float(np.mean(losses))
        for m in self._metrics:
            logs["eval_" + _mname(m)] = m.accumulate()
        cbks.on_eval_end(logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            eval_data = DataLoader(eval_data, batch_size=batch_size,
                                   num_workers=num_workers)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                log_freq=log_freq)
        return self._run_eval(eval_data, cbks)

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            test_data = DataLoader(test_data, batch_size=batch_size,
                                   num_workers=num_workers)
        outputs = []
        for batch in test_data:
            ins, _ = _split_batch(batch, has_label=False)
            out = self.predict_batch(ins)
            outputs.append(np.asarray(unwrap(out)) if not isinstance(out, (list, tuple))
                           else [np.asarray(unwrap(o)) for o in out])
        if stack_outputs and outputs and not isinstance(outputs[0], list):
            return [np.concatenate(outputs, axis=0)]
        return [outputs]

    # ---- persistence ----------------------------------------------------------
    def save(self, path, training=True):
        from ..utils.checkpoint import save as _save
        _save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..utils.checkpoint import load as _load
        self.network.set_state_dict(_load(path + ".pdparams"))
        import os
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(_load(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtype)


def _as_tensor(x):
    if isinstance(x, Tensor):
        return x
    return Tensor(np.asarray(x))


def _mname(m):
    n = m.name()
    return n if isinstance(n, str) else n[0]


def _split_batch(batch, has_label=True):
    if isinstance(batch, (list, tuple)):
        if has_label and len(batch) >= 2:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), []
    return [batch], []
