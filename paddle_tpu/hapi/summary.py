"""Per-layer model summary + FLOPs counting.

Reference: python/paddle/hapi/model_summary.py (layer table with output
shapes/params) and python/paddle/hapi/dynamic_flops.py:1 (per-layer flops via
forward hooks).  Same mechanism here: forward-post hooks on leaf sublayers
record output shapes; flops rules follow the reference's MAC accounting
(conv: out_elems * Cin/groups * kh * kw; linear: out_elems * in_features;
norms/activations: numel).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor


def _numel(shape) -> int:
    return int(np.prod(shape)) if shape else 1


def _out_shape(out):
    if isinstance(out, (tuple, list)):
        out = out[0]
    return tuple(out.shape)


def _layer_flops(layer, inputs, out_shape) -> int:
    name = type(layer).__name__
    out_elems = _numel(out_shape)
    if name == "Linear":
        return out_elems * layer.weight.shape[0]
    if name in ("Conv2DTranspose", "Conv1DTranspose", "Conv3DTranspose"):
        # weight is (in_c, out_c/groups, *k): every input element feeds
        # out_c/groups * prod(k) outputs -> MACs = in_elems * numel(w[1:])
        if not inputs:
            return 0
        in_elems = _numel(tuple(inputs[0].shape))
        return in_elems * _numel(layer.weight.shape[1:])
    if name in ("Conv2D", "Conv1D", "Conv3D"):
        w = layer.weight.shape  # (out_c, in_c/groups, *k)
        kernel_ops = _numel(w[1:])
        return out_elems * kernel_ops
    if name in ("BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
                "LayerNorm", "GroupNorm", "InstanceNorm2D", "SyncBatchNorm"):
        return 2 * out_elems
    if name in ("ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax",
                "LeakyReLU", "Hardswish", "Hardsigmoid", "SiLU", "Swish",
                "AvgPool2D", "MaxPool2D", "AdaptiveAvgPool2D",
                "AdaptiveMaxPool2D"):
        return out_elems
    if name == "Embedding":
        return 0
    return 0


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    """paddle.summary: per-layer table; returns {'total_params',
    'trainable_params', 'total_flops'}."""
    records = []
    hooks = []

    def make_hook(lname):
        def hook(layer, inputs, out):
            try:
                oshape = _out_shape(out)
            except Exception:
                oshape = ()
            n_params = sum(_numel(p.shape)
                           for p in layer._parameters.values()
                           if p is not None)
            records.append((lname, type(layer).__name__, oshape, n_params,
                            _layer_flops(layer, inputs, oshape)))
        return hook

    for lname, sub in net.named_sublayers():
        if next(sub.children(), None) is None:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(lname)))

    x = input
    if x is None and input_size is None:
        # params-only summary (no forward, so no shapes/flops)
        for h in hooks:
            h.remove()
        total = sum(_numel(p.shape) for _, p in net.named_parameters())
        trainable = sum(_numel(p.shape) for _, p in net.named_parameters()
                        if p.trainable)
        print(f"Total params: {total:,}\nTrainable params: {trainable:,}")
        return {"total_params": total, "trainable_params": trainable,
                "total_flops": 0}
    if x is None:
        sizes = (input_size if isinstance(input_size, (list, tuple))
                 and isinstance(input_size[0], (list, tuple))
                 else [input_size])
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes] * len(sizes)
        x = [Tensor(np.zeros(s, dtype=(d or "float32"))) for s, d in
             zip(sizes, dts)]
    elif not isinstance(x, (list, tuple)):
        x = [x]

    was_training = net.training
    net.eval()
    try:
        from ..core.tensor import no_grad
        with no_grad():
            net(*x)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(_numel(p.shape) for _, p in net.named_parameters())
    trainable = sum(_numel(p.shape) for _, p in net.named_parameters()
                    if p.trainable)
    total_flops = sum(r[4] for r in records)

    w_name = max([len(f"{r[0]} ({r[1]})") for r in records], default=24) + 2
    lines = ["-" * (w_name + 50)]
    lines.append(f"{'Layer (type)':<{w_name}}{'Output Shape':<22}"
                 f"{'Params':>12}{'FLOPs':>14}")
    lines.append("-" * (w_name + 50))
    for lname, cls, oshape, n_params, fl in records:
        lines.append(f"{lname + ' (' + cls + ')':<{w_name}}"
                     f"{str(list(oshape)):<22}{n_params:>12,}{fl:>14,}")
    lines.append("-" * (w_name + 50))
    lines.append(f"Total params: {total:,}")
    lines.append(f"Trainable params: {trainable:,}")
    lines.append(f"Non-trainable params: {total - trainable:,}")
    lines.append(f"Total FLOPs (MAC-counted): {total_flops:,}")
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable,
            "total_flops": total_flops}


def flops(net, input_size, dtypes=None, print_detail: bool = False) -> int:
    """paddle.flops (reference: hapi/dynamic_flops.py:flops)."""
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        info = summary(net, input_size, dtypes)
    if print_detail:
        print(buf.getvalue())
    return info["total_flops"]
