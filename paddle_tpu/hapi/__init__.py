"""paddle_tpu.hapi (reference: python/paddle/hapi/)."""
from .model import Model, summary_fn as summary  # noqa: F401
from . import callbacks  # noqa: F401
