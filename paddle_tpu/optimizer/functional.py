"""Shared functional optimizer-update core.

Single source of truth for the per-parameter update loop (weight-decay
gating via apply_decay_param_fun, coupled L2, decoupled AdamW decay) used by
every compiled step: Optimizer.step (eager), jit.TrainStep,
parallel.ShardedTrainStep and parallel.pipeline.PipelinedTrainStep.
"""
from __future__ import annotations

from typing import Dict

import jax.numpy as jnp


def decay_flags(optimizer, names) -> Dict[str, bool]:
    """Resolve apply_decay_param_fun per param name (True = decay applies)."""
    return {n: optimizer._decay_applies(n) for n in names}


def apply_updates(optimizer, params: dict, grads: dict, opt_state: dict,
                  lr, step_no, decay: Dict[str, bool],
                  lr_mults: Dict[str, float] = None,
                  row_shard: Dict[str, tuple] = None):
    """Pure: returns (new_params, new_opt_state) for the keys in `grads`.

    Params without grads pass through unchanged.  `row_shard` maps param
    names to (mesh_axis, mesh) for mesh row-sharded embedding tables
    (embedding.ShardedEmbedding): their RowSparseGrads take the per-shard
    lazy update (each shard touches only its own rows) instead of the
    whole-table one.
    """
    from ..core.selected_rows import RowSparseGrad
    from .sparse import lazy_row_update
    wd = getattr(optimizer, "_wd", 0.0)
    wd_l1 = getattr(optimizer, "_wd_mode", "l2") == "l1"
    dwd = getattr(optimizer, "_decoupled_wd", 0.0)
    new_params = dict(params)
    new_opt = dict(opt_state)
    for k, g in grads.items():
        p = params[k]
        if isinstance(g, RowSparseGrad):
            if not optimizer._elementwise_update:
                g = g.to_dense()  # Lamb/Lars need full-tensor norms
            else:
                # SelectedRows path: lazy row-wise update (adam_op.h
                # lazy_mode); row-sharded tables update per mesh shard
                rs = (row_shard or {}).get(k)
                if rs is not None:
                    from ..embedding.functional import sharded_lazy_row_update
                    axis, mesh = rs
                    new_params[k], new_opt[k] = sharded_lazy_row_update(
                        optimizer, p, g, opt_state[k], lr, step_no, axis,
                        mesh, decay.get(k, True),
                        (lr_mults or {}).get(k, 1.0))
                    continue
                new_params[k], new_opt[k] = lazy_row_update(
                    optimizer, p, g, opt_state[k], lr, step_no,
                    decay.get(k, True), (lr_mults or {}).get(k, 1.0))
                continue
        is_float = jnp.issubdtype(p.dtype, jnp.floating)
        db = decay.get(k, True)
        m = (lr_mults or {}).get(k, 1.0)
        if wd and db and is_float:
            g = g + wd * (jnp.sign(p) if wd_l1 else p)
        np_, ns = optimizer.update_one(p, g, opt_state[k], lr * m, step_no)
        if dwd and db and is_float:
            np_ = (np_.astype(jnp.float32)
                   - lr * m * dwd * p.astype(jnp.float32)).astype(p.dtype)
        new_params[k] = np_
        new_opt[k] = ns
    return new_params, new_opt
