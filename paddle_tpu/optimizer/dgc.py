"""Deep Gradient Compression momentum optimizer.

Reference: python/paddle/fluid/optimizer.py:1183 (DGCMomentumOptimizer) and
operators/dgc_op — momentum correction + top-k gradient sparsification with
error feedback (Lin et al., DGC).

TPU-native notes: the reference's win is sending only the top-k values over
slow interconnects; on TPU the collective itself rides ICI (and bf16 wire
compression is ShardedTrainStep's fp16_allreduce flag), so what this class
preserves is the ALGORITHM's semantics — sparsified velocity application with
residual accumulation — with static shapes: the mask comes from a quantile
threshold, not a dynamic top-k gather.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class DGCMomentum(Optimizer):
    """Momentum with top-k sparsified updates + error feedback."""

    _elementwise_update = False  # quantile threshold is a full-tensor stat

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 sparsity=0.999, rampup_begin_step=0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = float(momentum)
        self._nesterov = bool(use_nesterov)
        if isinstance(sparsity, (list, tuple)):
            sparsity = sparsity[-1]
        self._sparsity = float(sparsity)
        self._rampup_begin = int(rampup_begin_step)

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        u = self._momentum * state["velocity"] + g32
        if self._nesterov:
            applied_dense = g32 + self._momentum * u
        else:
            applied_dense = u

        if p.ndim == 0 or p.size < 2:
            # tiny params run plain momentum (velocity persists)
            return (p.astype(jnp.float32) - lr * applied_dense).astype(
                p.dtype), {"velocity": u}

        # top-k selection via quantile threshold (static shapes on TPU)
        thresh = jnp.quantile(jnp.abs(u).reshape(-1).astype(jnp.float32),
                              self._sparsity)
        rampup = step <= self._rampup_begin
        mask = jnp.logical_or(jnp.abs(u) >= thresh, rampup)
        applied = jnp.where(mask, applied_dense, 0.0)
        # DGC phase: sent velocity is cleared (error feedback keeps the
        # rest); ramp-up phase: plain Momentum, velocity persists
        new_u = jnp.where(jnp.logical_and(mask, jnp.logical_not(rampup)),
                          0.0, u)
        return (p.astype(jnp.float32) - lr * applied).astype(p.dtype), \
            {"velocity": new_u}
