"""Lazy row-wise optimizer update for RowSparseGrad params.

Reference: paddle/fluid/operators/optimizers/adam_op.h:1 (lazy_mode — only
rows present in the SelectedRows grad get their moments/param updated) and
paddle/fluid/operators/math/selected_rows_functor.cc (scatter::MergeAdd).

TPU-native: `merge_rows` segment-sums duplicate lookup ids into static-shape
buffers (invalid tail slots get an out-of-range sentinel id), then the update
gathers only the touched param/state rows, runs the optimizer's scalar-free
`update_one` on the (N, width) slab, and scatters back with `mode="drop"` so
sentinel rows vanish.  Work is O(lookups·width), not O(height·width).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.selected_rows import RowSparseGrad


def merge_rows(rows, values, height: int):
    """SelectedRows MergeAdd: sum duplicate row entries.

    Returns (uids, summed): uids (N,) int32 where the first k slots hold the
    unique row ids and the rest the sentinel `height`; summed (N, width) f32
    holds the per-unique-row gradient sums in the matching slots.
    """
    n = rows.shape[0]
    order = jnp.argsort(rows)
    sr = rows[order]
    sv = values[order].astype(jnp.float32)
    is_rep = jnp.concatenate(
        [jnp.ones((1,), bool), sr[1:] != sr[:-1]]) if n > 1 else \
        jnp.ones((n,), bool)
    seg = jnp.cumsum(is_rep) - 1
    summed = jax.ops.segment_sum(sv, seg, num_segments=n)
    uids = jax.ops.segment_max(sr, seg, num_segments=n)
    valid = jnp.arange(n) < seg[-1] + 1
    uids = jnp.where(valid, uids, height)
    return uids.astype(jnp.int32), summed


def _row_leaf(s, height: int) -> bool:
    return (hasattr(s, "shape") and getattr(s, "ndim", 0) >= 1
            and s.shape[0] == height)


def lazy_row_update(optimizer, p, grad: RowSparseGrad, state, lr, step_no,
                    decay_flag: bool = True, lr_mult: float = 1.0):
    """Pure: (new_param, new_state) touching only the grad's rows."""
    height = p.shape[0]
    uids, g = merge_rows(grad.rows, grad.values, height)
    safe = jnp.clip(uids, 0, height - 1)

    p_rows = p[safe]
    state_rows = jax.tree_util.tree_map(
        lambda s: s[safe] if _row_leaf(s, height) else s, state)

    wd = getattr(optimizer, "_wd", 0.0)
    wd_l1 = getattr(optimizer, "_wd_mode", "l2") == "l1"
    dwd = getattr(optimizer, "_decoupled_wd", 0.0)
    if wd and decay_flag:
        pr = p_rows.astype(jnp.float32)
        g = g + wd * (jnp.sign(pr) if wd_l1 else pr)
    new_rows, ns_rows = optimizer.update_one(p_rows, g, state_rows,
                                             lr * lr_mult, step_no)
    if dwd and decay_flag:
        new_rows = (new_rows.astype(jnp.float32)
                    - lr * lr_mult * dwd * p_rows.astype(jnp.float32)
                    ).astype(p_rows.dtype)

    new_p = p.at[uids].set(new_rows.astype(p.dtype), mode="drop")
    new_state = jax.tree_util.tree_map(
        lambda s, ns: s.at[uids].set(ns, mode="drop")
        if _row_leaf(s, height) else ns, state, ns_rows)
    return new_p, new_state
