"""paddle_tpu.optimizer (reference: python/paddle/optimizer/)."""
from . import lr  # noqa: F401
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,  # noqa: F401
                        Adagrad, RMSProp, Adadelta, Lamb, LarsMomentum,
                        Ftrl, DecayedAdagrad, Dpsgd)
from .averaging import ExponentialMovingAverage, ModelAverage, Lookahead  # noqa: F401
from .dgc import DGCMomentum  # noqa: F401
