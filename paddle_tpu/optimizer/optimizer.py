"""Optimizers.

Reference: python/paddle/optimizer/ + device update kernels in
paddle/fluid/operators/optimizers/ (sgd/momentum/adam/lamb/... CUDA kernels).
TPU-native design: each optimizer defines a *pure functional* update
(`init_state` / `update_one`) over raw jax arrays; the eager `.step()` applies
it to the whole parameter pytree in ONE jitted XLA call (the analogue of the
reference's fused optimizer kernels), and the same pure core is reused by the
jit training path (paddle_tpu.jit.TrainStep) and by the FSDP/ZeRO sharding
layer, where XLA partitions the update across the mesh.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .lr import LRScheduler


class Optimizer:
    """Base optimizer with paddle's eager API (step/clear_grad/minimize)."""

    # True when update_one is purely element-wise, which the lazy sparse
    # row update requires (it feeds update_one a touched-rows slab, not the
    # full parameter); Lamb/Lars compute full-tensor norms, so their sparse
    # grads are densified instead.
    _elementwise_update = True

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._name = name
        # weight_decay: float -> L2 coefficient added to grads (paddle
        # regularizer semantics); AdamW overrides with decoupled decay.
        # An L1Decay object switches the penalty to coeff * sign(w)
        # (reference: regularizer.py append_regularization_ops).
        self._wd_mode = "l2"
        if weight_decay is None:
            self._wd = 0.0
        elif isinstance(weight_decay, (int, float)):
            self._wd = float(weight_decay)
        else:  # L1Decay/L2Decay object
            self._wd = float(getattr(weight_decay, "_coeff",
                                     getattr(weight_decay, "coeff", 0.0)))
            self._wd_mode = getattr(weight_decay, "mode", "l2") or "l2"
        self._step_count = 0
        self._states: Dict[int, dict] = {}
        # jitted tree-update closures keyed by (n_params, lr_mults, decay_bits)
        # — the closure bakes those in, so a changed grad-bearing param set
        # must map to a fresh closure, not silently reuse a stale one.
        self._jit_cache: Dict[tuple, object] = {}
        self._accumulators: Dict[str, Dict[int, Tensor]] = {}
        # decoupled (AdamW-style) decay coefficient; 0 on plain optimizers
        self._decoupled_wd = 0.0

    def _decay_applies(self, name) -> bool:
        """Whether weight decay applies to the param with this name
        (AdamW's apply_decay_param_fun hook; True for plain optimizers)."""
        return True

    # ---- functional core (override in subclasses) -------------------------
    def init_state(self, p) -> dict:
        return {}

    def update_one(self, p, g, state: dict, lr, step) -> tuple:
        """(new_param, new_state) from raw arrays. Pure."""
        raise NotImplementedError

    # ---- lr ----------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    @property
    def _lr_scheduler(self):
        return (self._learning_rate
                if isinstance(self._learning_rate, LRScheduler) else None)

    # ---- eager step ---------------------------------------------------------
    def step(self):
        from ..core.selected_rows import RowSparseGrad
        params = [p for p in self._parameter_list
                  if p.trainable and p.grad is not None]
        if not params:
            self._step_count += 1
            return
        grads = [p.grad for p in params]
        # resolve the EFFECTIVE clip up front: the optimizer's own, else
        # the era program-global from fluid.clip.set_gradient_clip (the
        # reference documents the optimizer's grad_clip as higher
        # priority) — the sparse densify guard below must see it too, or
        # sparse grads would silently bypass the global clip
        clip = self._grad_clip
        if clip is None:
            from ..nn import clip as _clip_mod
            clip = getattr(_clip_mod, "_global_gradient_clip", None)
        # SelectedRows grads take the lazy row-wise path; a grad_clip
        # densifies them first (the reference likewise forbids global-norm
        # clipping over sparse grads).
        sparse = [(i, g) for i, g in enumerate(grads)
                  if isinstance(g, RowSparseGrad)]
        if sparse and (clip is not None
                       or not self._elementwise_update):
            for i, g in sparse:
                grads[i] = Tensor(g.to_dense(), stop_gradient=True)
            sparse = []
        if sparse:
            from .sparse import lazy_row_update
            lr = jnp.asarray(self.get_lr(), jnp.float32)
            step = jnp.asarray(self._step_count + 1, jnp.int32)
            for i, g in sorted(sparse, reverse=True):
                p = params[i]
                db = self._decay_applies(getattr(p, "name", None))
                oa = getattr(p, "optimize_attr", None)
                m = float(oa.get("learning_rate", 1.0)) if oa else 1.0
                axis = getattr(p, "row_shard_axis", None)
                mesh = getattr(p, "row_shard_mesh", None)
                key = ("sparse", db, m, axis, id(mesh) if mesh else None)
                fn = self._jit_cache.get(key)
                if fn is None:
                    if axis is not None and mesh is not None:
                        # mesh row-sharded table: per-shard lazy update
                        from ..embedding.functional import \
                            sharded_lazy_row_update
                        fn = jax.jit(
                            lambda pv, gv, sv, lrv, stv, _db=db, _m=m,
                            _ax=axis, _me=mesh:
                            sharded_lazy_row_update(self, pv, gv, sv, lrv,
                                                    stv, _ax, _me, _db, _m))
                    else:
                        fn = jax.jit(
                            lambda pv, gv, sv, lrv, stv, _db=db, _m=m:
                            lazy_row_update(self, pv, gv, sv, lrv, stv,
                                            _db, _m))
                    self._jit_cache[key] = fn
                new_p, ns = fn(p._data, g, self._get_state(p), lr, step)
                p._set_data(new_p)
                self._states[id(p)] = ns
                del params[i], grads[i]
            if not params:
                self._step_count += 1
                return
        if clip is not None:
            pg = clip(list(zip(params, grads)))
            grads = [g for _, g in pg]
        # decoupled regularizer path: per-param regularizer overrides global wd
        self._step_count += 1
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)

        p_raw = [p._data for p in params]
        g_raw = [g._data for g in grads]
        states = [self._get_state(p) for p in params]
        # plain Tensors (to_tensor(stop_gradient=False)) are optimizable
        # too, like the reference — they just lack Parameter attrs
        lr_mults = tuple(float(getattr(p, "optimize_attr", None)
                               .get("learning_rate", 1.0)
                               if getattr(p, "optimize_attr", None)
                               else 1.0) for p in params)
        decay_bits = tuple(self._decay_applies(getattr(p, "name", None))
                           for p in params)
        # per-param ParamAttr(regularizer=...) overrides the optimizer-level
        # decay (reference: append_regularization_ops picks the param's own
        # regularizer first)
        per_wd = tuple(
            (float(getattr(r, "_coeff", 0.0)),
             getattr(r, "mode", "l2") == "l1")
            if (r := getattr(p, "regularizer", None)) is not None else None
            for p in params)

        cache_key = (len(params), lr_mults, decay_bits, per_wd)
        jit_update = self._jit_cache.get(cache_key)
        if jit_update is None:
            wd, dwd = self._wd, self._decoupled_wd
            wd_l1 = self._wd_mode == "l1"
            def _tree_update(p_raw, g_raw, states, lr, step):
                outs, new_states = [], []
                for p, g, s, m, db, pw in zip(p_raw, g_raw, states,
                                              lr_mults, decay_bits, per_wd):
                    is_float = jnp.issubdtype(p.dtype, jnp.floating)
                    w_coeff, w_l1 = (wd, wd_l1) if pw is None else pw
                    if w_coeff and db and is_float:
                        g = g + w_coeff * (jnp.sign(p) if w_l1 else p)
                    np_, ns = self.update_one(p, g, s, lr * m, step)
                    if dwd and db and is_float:
                        np_ = (np_.astype(jnp.float32)
                               - lr * m * dwd * p.astype(jnp.float32)
                               ).astype(p.dtype)
                    outs.append(np_)
                    new_states.append(ns)
                return outs, new_states
            jit_update = self._jit_cache[cache_key] = jax.jit(_tree_update)

        new_p, new_states = jit_update(p_raw, g_raw, states, lr, step)
        for p, np_, ns in zip(params, new_p, new_states):
            p._set_data(np_)
            self._states[id(p)] = ns

    def _get_state(self, p):
        s = self._states.get(id(p))
        if s is None:
            s = self.init_state(p._data)
            self._states[id(p)] = s
        return s

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list or []:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Dygraph minimize: backward + step (reference fluid Optimizer.minimize)."""
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list or []]

    # ---- state dict ----------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                s = self._states.get(id(p))
                if s:
                    for k, v in s.items():
                        out[f"param{i}.{k}"] = Tensor(v)
        sched = self._lr_scheduler
        if sched is not None:
            out["LR_Scheduler"] = sched.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("_step_count", 0))
        if "LR_Scheduler" in state_dict and self._lr_scheduler is not None:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                st = {}
                prefix = f"param{i}."
                for k, v in state_dict.items():
                    if isinstance(k, str) and k.startswith(prefix):
                        st[k[len(prefix):]] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
                if st:
                    self._states[id(p)] = st
        self._jit_cache.clear()

    set_dict = set_state_dict


class SGD(Optimizer):
    """reference: operators/optimizers/sgd_op."""

    def update_one(self, p, g, state, lr, step):
        return p - lr.astype(p.dtype) * g.astype(p.dtype), state


class Momentum(Optimizer):
    """reference: operators/optimizers/momentum_op (incl. nesterov)."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def update_one(self, p, g, state, lr, step):
        g = g.astype(p.dtype)
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_p = p - lr.astype(p.dtype) * (g + self._momentum * v)
        else:
            new_p = p - lr.astype(p.dtype) * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """reference: operators/optimizers/adam_op (+ beta pow accumulators)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p, jnp.float32),
                "moment2": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment1": m, "moment2": v}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._decoupled_wd = float(weight_decay) if weight_decay else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_applies(self, name) -> bool:
        fn = self._apply_decay_param_fun
        if fn is None or not name:
            return True
        return bool(fn(name))


class Adamax(Optimizer):
    """reference: operators/optimizers/adamax_op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def init_state(self, p):
        return {"moment": jnp.zeros_like(p, jnp.float32),
                "inf_norm": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g32))
        t = step.astype(jnp.float32)
        upd = lr / (1 - self._beta1 ** t) * m / (u + self._eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    """reference: operators/optimizers/adagrad_op."""

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, p):
        return {"moment": jnp.full_like(p, self._init_acc, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        acc = state["moment"] + jnp.square(g32)
        upd = lr * g32 / (jnp.sqrt(acc) + self._eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), {"moment": acc}


class RMSProp(Optimizer):
    """reference: operators/optimizers/rmsprop_op (centered variant included)."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def init_state(self, p):
        s = {"mean_square": jnp.zeros_like(p, jnp.float32),
             "momentum": jnp.zeros_like(p, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(p, jnp.float32)
        return s

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g32)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        new_state = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            new_state["mean_grad"] = mg
        return (p.astype(jnp.float32) - mom).astype(p.dtype), new_state


class Adadelta(Optimizer):
    """reference: operators/optimizers/adadelta_op."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps = rho, epsilon

    def init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p, jnp.float32),
                "avg_squared_update": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * jnp.square(g32)
        upd = (jnp.sqrt(state["avg_squared_update"] + self._eps)
               / jnp.sqrt(asg + self._eps)) * g32
        asu = self._rho * state["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    """Layer-wise adaptive moments (reference: operators/optimizers/lamb_op)."""

    _elementwise_update = False  # trust ratio needs full-tensor norms

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._lamb_wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, p):
        return {"moment1": jnp.zeros_like(p, jnp.float32),
                "moment2": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * state["moment2"] + (1 - self._beta2) * jnp.square(g32)
        t = step.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + self._lamb_wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), \
            {"moment1": m, "moment2": v}


class LarsMomentum(Optimizer):
    """reference: operators/optimizers/lars_momentum_op."""

    _elementwise_update = False  # local lr needs full-tensor norms

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def init_state(self, p):
        return {"velocity": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        w_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm / (g_norm + self._lars_wd * w_norm + 1e-12),
            1.0)
        v = self._momentum * state["velocity"] + lr * local_lr * (
            g32 + self._lars_wd * p32)
        return (p32 - v).astype(p.dtype), {"velocity": v}


class DecayedAdagrad(Optimizer):
    """reference: operators/optimizers/decayed_adagrad_op +
    fluid/optimizer.py:2384 — moment = decay*moment + (1-decay)*g^2;
    p -= lr * g / (sqrt(moment) + eps)."""

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._decay, self._eps = decay, epsilon

    def init_state(self, p):
        return {"moment": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        m = self._decay * state["moment"] + (1 - self._decay) * jnp.square(g32)
        new_p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(m) + self._eps)
        return new_p.astype(p.dtype), {"moment": m}


class Dpsgd(Optimizer):
    """Differentially-private SGD (reference:
    operators/optimizers/dpsgd_op.h, CCS16 arXiv:1607.00133): per-tensor
    l2 clip to `clip`, ONE gaussian noise sample per tensor scaled by
    sigma/batch_size, p -= lr*(g/scale + noise/batch_size).  TPU-native:
    the noise rides core.rng (paddle.seed-reproducible) instead of the
    kernel's Box-Muller loop."""

    _elementwise_update = False  # per-tensor l2 norm

    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=0.9, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._clip, self._bs, self._sigma = clip, batch_size, sigma
        # noise root: drawn once at construction (paddle.seed-pinned);
        # per-step keys FOLD IN the traced step number below — calling
        # next_key() inside update_one would bake ONE constant key into
        # the jitted update and replay identical noise every step
        from ..core import rng as _rng
        self._noise_root = _rng.next_key()
        self._noise_site = 0  # trace-time per-parameter op counter

    def init_state(self, p):
        return {}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        l2 = jnp.sqrt(jnp.sum(jnp.square(g32)))
        scale = jnp.maximum(l2 / self._clip, 1.0)
        # distinct per parameter (trace-time site counter constant) AND
        # per step (traced step folds in at run time)
        self._noise_site += 1
        key = jax.random.fold_in(
            jax.random.fold_in(self._noise_root, self._noise_site), step)
        noise = self._sigma * jax.random.normal(key, ())
        new_p = (p.astype(jnp.float32)
                 - lr * (g32 / scale + noise / self._bs))
        return new_p.astype(p.dtype), {}


class Ftrl(Optimizer):
    """reference: operators/optimizers/ftrl_op."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def init_state(self, p):
        return {"squared": jnp.zeros_like(p, jnp.float32),
                "linear": jnp.zeros_like(p, jnp.float32)}

    def update_one(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        new_sq = state["squared"] + jnp.square(g32)
        lp = -self._lr_power
        sigma = (new_sq ** lp - state["squared"] ** lp) / lr
        new_lin = state["linear"] + g32 - sigma * p32
        pre = jnp.where(jnp.abs(new_lin) > self._l1,
                        (jnp.sign(new_lin) * self._l1 - new_lin)
                        / (new_sq ** lp / lr + 2 * self._l2),
                        0.0)
        return pre.astype(p.dtype), {"squared": new_sq, "linear": new_lin}
