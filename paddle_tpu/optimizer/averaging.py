"""Parameter averaging utilities: EMA and ModelAverage.

Reference: python/paddle/fluid/optimizer.py:3441 (ExponentialMovingAverage)
and :3132 (ModelAverage) — both keep device-side accumulators updated after
each optimizer step and expose apply()/restore() to swap the averaged weights
in for evaluation.

TPU-native: accumulators are plain jax arrays updated in one fused jitted
call per update(); under a sharded step they inherit the param shardings
(tree ops are sharding-preserving), so no host gather ever happens.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp


def _float_params(parameters):
    return [p for p in parameters
            if jnp.issubdtype(p._data.dtype, jnp.floating)]


class ExponentialMovingAverage:
    """shadow = decay * shadow + (1 - decay) * param, with the reference's
    optional step-based decay ramp thres_steps: min(decay, (1+t)/(10+t))."""

    def __init__(self, decay: float = 0.999, thres_steps: bool = False,
                 parameters=None, name: Optional[str] = None):
        if parameters is None:
            raise ValueError("EMA needs the parameter list")
        self._decay = float(decay)
        self._thres = bool(thres_steps)
        self._params = _float_params(parameters)
        self._step = 0
        self._shadow = [p._data for p in self._params]
        self._backup = None

        def _upd(shadow, params, decay):
            return [decay * s.astype(jnp.float32)
                    + (1.0 - decay) * p.astype(jnp.float32)
                    for s, p in zip(shadow, params)]
        self._jit_upd = jax.jit(_upd)

    def update(self):
        self._step += 1
        d = self._decay
        if self._thres:
            d = min(d, (1.0 + self._step) / (10.0 + self._step))
        self._shadow = self._jit_upd(
            self._shadow, [p._data for p in self._params],
            jnp.float32(d))

    @contextlib.contextmanager
    def apply(self, need_restore: bool = True):
        """Swap averaged weights in (usable as a context manager, matching
        the reference's apply/restore pair)."""
        self._backup = [p._data for p in self._params]
        for p, s in zip(self._params, self._shadow):
            p._set_data(s.astype(p._data.dtype))
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._set_data(b)
        self._backup = None

    def state_dict(self) -> Dict[str, object]:
        return {"step": self._step,
                "shadow": [jax.device_get(s) for s in self._shadow]}

    def set_state_dict(self, state):
        self._step = int(state["step"])
        self._shadow = [jnp.asarray(s) for s in state["shadow"]]


class ModelAverage:
    """Running sums with a sliding window (reference ModelAverage):
    keeps sum_1 (current block), sum_2/sum_3 (older blocks) and applies
    (sum_1+sum_2+sum_3)/num_accumulates when the window is in
    [min_average_window, max_average_window]."""

    def __init__(self, average_window_rate: float,
                 parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000,
                 name: Optional[str] = None):
        if parameters is None:
            raise ValueError("ModelAverage needs the parameter list")
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._params = _float_params(parameters)
        z = [jnp.zeros_like(p._data, jnp.float32) for p in self._params]
        self._sum1, self._sum2, self._sum3 = list(z), list(z), list(z)
        self._n1 = 0      # accumulates in sum_1
        self._n2 = 0      # accumulates in sum_2
        self._n3 = 0      # accumulates in sum_3
        self._backup = None

        def _acc(s1, params):
            return [s.astype(jnp.float32) + p.astype(jnp.float32)
                    for s, p in zip(s1, params)]
        self._jit_acc = jax.jit(_acc)

    @property
    def _window(self):
        total = self._n1 + self._n2 + self._n3
        return max(self._min_w, int(self._rate * total))

    def step(self):
        """Accumulate current params (call once per optimizer step)."""
        self._sum1 = self._jit_acc(self._sum1,
                                   [p._data for p in self._params])
        self._n1 += 1
        if self._n1 >= min(self._max_w, self._window):
            # rotate blocks: sum_3 <- sum_2, sum_2 <- sum_1 (reference
            # average_accumulates_op semantics)
            self._sum3, self._n3 = self._sum2, self._n2
            self._sum2, self._n2 = self._sum1, self._n1
            self._sum1 = [jnp.zeros_like(p._data, jnp.float32)
                          for p in self._params]
            self._n1 = 0

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        n = self._n1 + self._n2 + self._n3
        if n == 0:
            yield self
            return
        self._backup = [p._data for p in self._params]
        for p, s1, s2, s3 in zip(self._params, self._sum1, self._sum2,
                                 self._sum3):
            avg = (s1 + s2 + s3) / n
            p._set_data(avg.astype(p._data.dtype))
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p, b in zip(self._params, self._backup):
            p._set_data(b)
        self._backup = None


class Lookahead:
    """Lookahead wrapper (reference: fluid/optimizer.py LookaheadOptimizer,
    arXiv:1907.08610): the inner ("fast") optimizer steps normally; every
    k steps the slow weights move slow += alpha*(fast - slow) and the fast
    weights reset to them."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if inner_optimizer is None:
            raise ValueError("Lookahead needs an inner optimizer")
        self.inner_optimizer = inner_optimizer
        self.alpha, self.k = float(alpha), int(k)
        self._params = _float_params(inner_optimizer._parameter_list or [])
        self._slow = [p._data for p in self._params]
        self._n = 0

    def step(self):
        self.inner_optimizer.step()
        self._n += 1
        if self._n % self.k == 0:
            for i, p in enumerate(self._params):
                slow = (self._slow[i]
                        + self.alpha * (p._data.astype(self._slow[i].dtype)
                                        - self._slow[i]))
                self._slow[i] = slow
                p._set_data(slow.astype(p._data.dtype))

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # base Optimizer.minimize contract: (optimize_ops, params_grads),
        # grads left inspectable
        loss.backward()
        self.step()
        return None, [(p, p.grad)
                      for p in self.inner_optimizer._parameter_list or []]

    def get_lr(self):
        return self.inner_optimizer.get_lr()
