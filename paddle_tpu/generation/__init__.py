"""Text generation: greedy / top-k / top-p sampling and beam search.

Reference surface: fluid's BeamSearchDecoder/dynamic_decode
(python/paddle/fluid/layers/rnn.py:1) backed by the beam_search op
(paddle/fluid/operators/math/beam_search.cc:1) — a host-stepped loop over
growing LoD beam state.  TPU-native redesign: the WHOLE decode loop is one
compiled XLA program — `lax.scan` over a fixed token budget with a
preallocated kv cache written via dynamic_update_slice; beams live as a
(batch*beam) leading axis and hypothesis reordering is a gather.  The
RNN-cell-shaped `BeamSearchDecoder` API lives in paddle_tpu.nn.decode.

Model protocol: `model.gen_fixed_cache(batch, max_len)` returns per-layer
(kbuf, vbuf) raw-array buffers; `model.forward_fixed(ids, caches, pos)`
returns (logits, new_caches) with the chunk written at [pos, pos+s).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap
from .speculative import (commit_speculative_greedy,  # noqa: F401
                          commit_speculative_sampled)

__all__ = ["generate", "apply_top_k", "apply_top_p",
           "apply_top_k_dynamic", "apply_top_p_dynamic",
           "process_logits_dynamic",
           "commit_speculative_greedy", "commit_speculative_sampled"]

_NEG = -1e9


def apply_top_k(logits, k):
    """Mask all but the k largest logits per row to -inf."""
    if k <= 0:
        return logits
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, _NEG, logits)


def _nucleus_cutoff(logits, p):
    """Per-row logit cutoff for nucleus filtering: the smallest logit in
    the shortest sorted prefix whose cumulative probability exceeds p.
    `p` may be a scalar or a per-row (B,) array (broadcast against the
    sorted (B, V) distribution) — the serving decode step passes per-slot
    p values through one shared trace."""
    sort = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sort, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep entries where the cumulative mass BEFORE them is < p; the top
    # token always survives (p=0 must mean greedy, not uniform)
    keep = (cum - probs) < jnp.asarray(p)[..., None]
    keep = keep.at[..., 0].set(True)
    return jnp.min(jnp.where(keep, sort, jnp.inf), axis=-1, keepdims=True)


def apply_top_p(logits, p):
    """Nucleus filtering: keep the smallest prefix of the sorted
    distribution whose cumulative probability exceeds p."""
    if p >= 1.0:
        return logits
    return jnp.where(logits < _nucleus_cutoff(logits, p), _NEG, logits)


def apply_top_k_dynamic(logits, k):
    """apply_top_k with a per-row (B,) TRACED k: rows with k <= 0 pass
    through unfiltered.  Static-k callers keep apply_top_k (lax.top_k is
    cheaper than the full sort); the serving decode step uses this form so
    heterogeneous per-slot k values share one compiled program."""
    v = logits.shape[-1]
    sort = jnp.sort(logits, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sort, jnp.clip(k - 1, 0, v - 1)[..., None], axis=-1)
    return jnp.where((k > 0)[..., None] & (logits < kth), _NEG, logits)


def apply_top_p_dynamic(logits, p):
    """apply_top_p with a per-row (B,) TRACED p: rows with p >= 1.0 keep
    the whole distribution (their cutoff lands on the smallest logit)."""
    return jnp.where(logits < _nucleus_cutoff(logits, p), _NEG, logits)


def process_logits_dynamic(logits, temperature, top_k, top_p, greedy):
    """_process_logits with every sampling knob a per-row dynamic input:
    temperature (B,) f32 (1.0 = untempered), top_k (B,) i32 (0 = off),
    top_p (B,) f32 (1.0 = off), greedy (B,) bool (True rows bypass the
    whole pipeline, matching the static greedy trace).  This is what lets
    the serving engine run heterogeneous requests through ONE decode
    program instead of one trace per sampling configuration."""
    proc = logits / temperature[..., None]
    proc = apply_top_k_dynamic(proc, top_k)
    proc = apply_top_p_dynamic(proc, top_p)
    return jnp.where(greedy[..., None], logits, proc)


def _process_logits(logits, temperature, top_k, top_p, greedy):
    if greedy:
        return logits
    if temperature not in (None, 1.0):
        logits = logits / jnp.float32(temperature)
    if top_k:
        logits = apply_top_k(logits, int(top_k))
    if top_p is not None and top_p < 1.0:
        logits = apply_top_p(logits, float(top_p))
    return logits


def beam_step(logp, scores, finished, keep_token):
    """One beam-search selection step over raw arrays (shared by the jitted
    generate() loop and nn.decode.BeamSearchDecoder).

    logp: (B, K, V) per-beam next-token log-probs; scores: (B, K) running
    totals; finished: (B, K) bool.  Finished beams may only extend with
    `keep_token` at zero added cost.  Returns (new_scores, token, parent,
    flat_src, parent_finished) where flat_src are (B*K,) gather indices for
    reordering any per-hypothesis state (kv caches, cell states).
    """
    b, k, vocab = logp.shape
    fin_row = jnp.full((vocab,), _NEG, jnp.float32).at[keep_token].set(0.0)
    logp = jnp.where(finished[:, :, None], fin_row[None, None], logp)
    cand = scores[:, :, None] + logp
    new_scores, top_ix = jax.lax.top_k(cand.reshape(b, k * vocab), k)
    parent = top_ix // vocab
    token = (top_ix % vocab).astype(jnp.int32)
    parent_finished = jnp.take_along_axis(finished, parent, axis=1)
    flat_src = (jnp.arange(b)[:, None] * k + parent).reshape(-1)
    return new_scores, token, parent, flat_src, parent_finished


def _model_fns(model):
    from ..jit import functional_call, state_arrays

    def apply_fixed(state, ids, caches, pos):
        return functional_call(model, state, ids, caches, pos,
                               training=False, method="forward_fixed")
    return state_arrays(model), apply_fixed


def _cached_jit(model, cfg_key, fn):
    """Per-model cache of compiled decode loops: generate() with the same
    shapes/strategy must not re-trace on every call (a fresh closure would
    defeat jax.jit's cache in serving loops)."""
    cache = model.__dict__.setdefault("_generate_jit_cache", {})
    jitted = cache.get(cfg_key)
    if jitted is None:
        jitted = cache[cfg_key] = jax.jit(fn)
    return jitted


def generate(model, input_ids, max_length=None, max_new_tokens=None,
             decode_strategy: str = "greedy_search", temperature=1.0,
             top_k=0, top_p=1.0, num_beams=1, length_penalty=0.0,
             eos_token_id: Optional[int] = None, pad_token_id: int = 0,
             bos_token_id=None, seed=None):
    """Decode continuations of `input_ids` (B, S).

    Returns (ids, scores): ids (B, max_new) generated tokens (pad after
    eos), scores (B,) the sequence log-prob of the emitted tokens (for
    sampling, under the tempered/filtered distribution they were drawn
    from).  decode_strategy: "greedy_search" | "sampling" | "beam_search".
    The full loop (prefill + scan over steps) runs as compiled XLA.
    """
    if decode_strategy not in ("greedy_search", "sampling", "beam_search"):
        raise ValueError(
            f"unknown decode_strategy {decode_strategy!r}: expected "
            "'greedy_search', 'sampling' or 'beam_search'")
    ids = unwrap(input_ids)
    if ids.ndim == 1:
        ids = ids[None]
    ids = ids.astype(jnp.int32)
    b, prompt_len = ids.shape
    if max_new_tokens is None:
        if max_length is None:
            raise ValueError("pass max_new_tokens or max_length")
        max_new_tokens = int(max_length) - prompt_len
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens <= 0:
        raise ValueError("nothing to generate")
    total = prompt_len + max_new_tokens
    eos = -1 if eos_token_id is None else int(eos_token_id)

    state, apply_fixed = _model_fns(model)
    strategy = decode_strategy
    if strategy == "beam_search":
        out, scores = _beam_search(
            state, apply_fixed, model, ids, max_new_tokens, total,
            int(num_beams), eos, int(pad_token_id), float(length_penalty))
    else:
        greedy = strategy == "greedy_search"
        if seed is not None:
            key = jax.random.PRNGKey(int(seed))
        else:
            from ..core import rng as _rng
            key = _rng.next_key()  # advances with paddle.seed's stream
        out, scores = _sample_loop(
            state, apply_fixed, model, ids, max_new_tokens, total, greedy,
            temperature, top_k, top_p, eos, int(pad_token_id), key)
    return Tensor(out), Tensor(scores)


def _sample_loop(state, apply_fixed, model, ids, max_new, total, greedy,
                 temperature, top_k, top_p, eos, pad, key):
    b, prompt_len_ = ids.shape
    caches = model.gen_fixed_cache(b, total)

    def run(state, ids, caches, key):
        logits, caches = apply_fixed(state, ids, caches, 0)  # prefill
        last = logits[:, -1, :].astype(jnp.float32)
        prompt_len = ids.shape[1]

        def body(carry, _):
            tok, caches, pos, key, finished, score, last = carry
            proc = _process_logits(last, temperature, top_k, top_p, greedy)
            key, sub = jax.random.split(key)
            if greedy:
                nxt = jnp.argmax(proc, axis=-1).astype(jnp.int32)
            else:
                nxt = jax.random.categorical(sub, proc).astype(jnp.int32)
            logp = jax.nn.log_softmax(proc, axis=-1)
            step_lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
            score = score + jnp.where(finished, 0.0, step_lp)
            nxt = jnp.where(finished, pad, nxt)
            finished = finished | (nxt == eos)

            # once EVERY row is finished the remaining iterations only
            # emit pad: skip the model call entirely (lax.cond executes
            # one branch at runtime — short completions inside a long
            # max_new_tokens budget stop paying full decode FLOPs).  The
            # zeroed last-logits are never observed: every later step has
            # finished all-True, so its sampled token is overridden by pad
            # and its score increment masked to 0.
            def live(ops):
                tok_, caches_ = ops
                logits, c2 = apply_fixed(state, tok_[:, None], caches_, pos)
                return logits[:, -1, :].astype(jnp.float32), c2

            def drained(ops):
                _, caches_ = ops
                return jnp.zeros_like(last), caches_

            nlast, caches = jax.lax.cond(jnp.all(finished), drained, live,
                                         (nxt, caches))
            return (nxt, caches, pos + 1, key, finished, score,
                    nlast), nxt

        init = (jnp.zeros((b,), jnp.int32), caches,
                jnp.int32(prompt_len), key,
                jnp.zeros((b,), bool), jnp.zeros((b,), jnp.float32), last)
        carry, toks = jax.lax.scan(body, init, None, length=max_new)
        return toks.T, carry[5]

    if greedy:  # tempering/filtering params don't affect the greedy trace
        cfg_key = ("sample", b, prompt_len_, max_new, total, True, eos, pad)
    else:
        cfg_key = ("sample", b, prompt_len_, max_new, total, False,
                   # None and 1.0 genuinely alias (both mean "no
                   # tempering"); 0.0 must NOT fold into them
                   float(1.0 if temperature is None else temperature),
                   int(top_k or 0),
                   float(1.0 if top_p is None else top_p), eos, pad)
    fn = _cached_jit(model, cfg_key, run)
    return fn(state, ids, caches, key)


def _beam_search(state, apply_fixed, model, ids, max_new, total, k, eos,
                 pad, length_penalty):
    """Batched beam search: hypotheses as a (B*K) leading axis; beam
    reordering is a gather on tokens + kv buffers (the XLA replacement for
    the reference's beam_search op LoD bookkeeping)."""
    b, prompt_len = ids.shape
    caches = model.gen_fixed_cache(b * k, total)

    def run(state, ids, caches):
        v_ids = jnp.repeat(ids, k, axis=0)  # (B*K, S)
        logits, caches = apply_fixed(state, v_ids, caches, 0)
        last = logits[:, -1, :].astype(jnp.float32)
        vocab = last.shape[-1]
        # beam 0 active, others -inf so step 1 picks distinct continuations
        scores = jnp.tile(jnp.array([0.0] + [_NEG] * (k - 1),
                                    jnp.float32), (b, 1))
        finished = jnp.zeros((b, k), bool)
        tok_buf = jnp.full((b, k, max_new), pad, jnp.int32)

        def body(carry, step):
            caches, scores, finished, tok_buf, last = carry
            logp = jax.nn.log_softmax(last, axis=-1).reshape(b, k, vocab)
            scores, tok, src_beam, flat_src, finished = beam_step(
                logp, scores, finished, keep_token=pad)
            tok_buf = jnp.take_along_axis(
                tok_buf, src_beam[:, :, None], axis=1)
            tok = jnp.where(finished, pad, tok)
            tok_buf = jax.lax.dynamic_update_index_in_dim(
                tok_buf, tok, step, axis=2)
            finished = finished | (tok == eos)

            caches = jax.tree_util.tree_map(
                lambda buf: jnp.take(buf, flat_src, axis=0), caches)
            logits, caches = apply_fixed(
                state, tok.reshape(-1)[:, None], caches,
                prompt_len + step)
            last = logits[:, -1, :].astype(jnp.float32)
            return (caches, scores, finished, tok_buf, last), None

        (caches, scores, finished, tok_buf, last), _ = jax.lax.scan(
            body, (caches, scores, finished, tok_buf, last),
            jnp.arange(max_new))

        if length_penalty:
            lens = jnp.sum((tok_buf != pad).astype(jnp.float32), axis=-1)
            lens = jnp.maximum(lens, 1.0)
            norm = jnp.power((5.0 + lens) / 6.0, length_penalty)
            ranked = scores / norm
        else:
            ranked = scores
        best = jnp.argmax(ranked, axis=1)  # (B,)
        out = jnp.take_along_axis(
            tok_buf, best[:, None, None], axis=1)[:, 0]
        sc = jnp.take_along_axis(ranked, best[:, None], axis=1)[:, 0]
        return out, sc

    fn = _cached_jit(model,
                     ("beam", b, prompt_len, max_new, total, k, eos, pad,
                      float(length_penalty)), run)
    return fn(state, ids, caches)
