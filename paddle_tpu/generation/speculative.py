"""Speculative decoding: in-program accept/reject over draft proposals.

Reference scheme: Leviathan et al. 2023 ("Fast Inference from Transformers
via Speculative Decoding") and Chen et al. 2023 ("Accelerating Large
Language Model Decoding with Speculative Sampling").  A cheap draft model
proposes K tokens; the target model scores all K proposals (plus the
preceding committed token) in ONE forward of K+1 positions; the longest
prefix of proposals the target agrees with is committed, plus one
corrected/bonus token drawn from the target.  Decode therefore advances
1..K+1 tokens per target forward instead of exactly one — the lever that
amortizes the per-step weight traffic the serving ROADMAP item names.

Everything here is pure jnp on raw arrays, designed to be traced INTO the
serving engine's single verify program (`serving.engine` vmaps the model
calls and hands the batched logits to the commit functions below) — the
accept/reject is `lax`-masked arithmetic, never a host round-trip.

Correctness contracts:

- **Greedy** (`commit_speculative_greedy`): a proposal is accepted iff it
  equals the target's argmax at its position, and the correction token is
  the target argmax at the first disagreement.  By induction the committed
  stream is exactly the target-only greedy chain — bit-identical to a solo
  `generation.generate(decode_strategy='greedy_search')` run, regardless
  of what the draft proposes.
- **Sampling** (`commit_speculative_sampled`): distribution-preserving
  rejection sampling.  Proposal x_i ~ q_i (the draft's PROCESSED
  distribution — same per-slot temperature/top-k/top-p knobs as the
  target) is accepted with probability min(1, p_i(x_i) / q_i(x_i)); on
  the first rejection the correction is drawn from the residual
  norm(max(p_i - q_i, 0)), and when all K are accepted the bonus token is
  drawn from p_K (handled uniformly here by padding q with a zero row:
  the residual of p against 0 IS p).  The marginal distribution of every
  committed token is exactly the processed target distribution — the
  Leviathan/Chen theorem — so draft quality affects throughput only,
  never the output law.
- **Per-slot spec on/off**: rows with ``spec_on=False`` force zero
  accepts and draw their single committed token from p_0 with the SAME
  key fold the non-speculative decode step uses
  (``fold_in(key, pos)`` + categorical) — a sampled request with
  speculation disabled streams bit-identically to a plain
  continuous-batching engine with the same seed.

RNG discipline: all speculative randomness derives from
``kbase = fold_in(key, pos)`` (pos = the slot's KV length at the tick, so
ticks never collide) salted by stage: draft proposals fold ``(DRAFT, i)``,
acceptance uniforms fold ``ACCEPT``, residual corrections fold
``(RESIDUAL, n)``.  Deterministic per request seed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["SALT_DRAFT", "SALT_ACCEPT", "SALT_RESIDUAL",
           "draft_proposal_key", "commit_speculative_greedy",
           "commit_speculative_sampled"]

# fold_in salts: distinct consumption streams per speculative stage (the
# plain decode path consumes fold_in(key, pos) unsalted — spec-off rows
# reuse exactly that, see commit_speculative_sampled)
SALT_DRAFT = 0x5D
SALT_ACCEPT = 0x5A
SALT_RESIDUAL = 0x5E


def draft_proposal_key(key, pos, i):
    """Per-slot key for the draft's i-th proposal at KV length `pos`."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.fold_in(key, pos), SALT_DRAFT), i)


def _accept_count(acc):
    """(S, K) bool accept flags -> (S,) length of the accepted PREFIX
    (a rejection gates everything after it)."""
    return jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)


def _emit(props, plog, n, corr, pad_token):
    """Assemble the committed-token block: accepted proposals, then the
    correction at position n, then pad.  Returns (out (S, K+1) int32,
    count (S,), logp (S, K+1) under the processed target)."""
    s, k1 = plog.shape[0], plog.shape[1]
    j = jnp.arange(k1, dtype=jnp.int32)[None, :]
    props_ext = jnp.concatenate(
        [props, jnp.zeros((s, 1), props.dtype)], axis=1)
    out = jnp.where(j < n[:, None], props_ext,
                    jnp.where(j == n[:, None], corr[:, None],
                              jnp.int32(pad_token))).astype(jnp.int32)
    lp_full = jax.nn.log_softmax(plog, axis=-1)
    lp = jnp.take_along_axis(lp_full, out[..., None], axis=-1)[..., 0]
    lp = jnp.where(j <= n[:, None], lp, 0.0)
    return out, n + 1, lp


def commit_speculative_greedy(props, qs, plog, keys, pos, greedy, spec_on,
                              pad_token):
    """All-greedy fast path: pure argmax comparison, zero RNG ops in the
    trace (the engine selects it with a batch-level `lax.cond`, mirroring
    the plain decode step's all-greedy branch).

    props (S, K) draft proposals; plog (S, K+1, V) PROCESSED target
    logits (for greedy rows processing is the identity, so these are the
    raw logits the solo greedy loop argmaxes); qs/keys/greedy accepted
    for signature parity with the sampled path and ignored.

    Returns (out (S, K+1), count (S,), accepted (S,), last (S,),
    logp (S, K+1)) — `out[:, :count]` are the committed tokens, `last`
    the new last-committed token, `accepted` the accepted-proposal count
    (the accept-rate numerator).
    """
    del qs, keys, pos, greedy
    k = props.shape[1]
    tops = jnp.argmax(plog, axis=-1).astype(jnp.int32)     # (S, K+1)
    acc = (props == tops[:, :k]) & spec_on[:, None]
    n = _accept_count(acc)
    corr = jnp.take_along_axis(tops, n[:, None], axis=1)[:, 0]
    out, count, lp = _emit(props, plog, n, corr, pad_token)
    return out, count, n, corr, lp


def commit_speculative_sampled(props, qs, plog, keys, pos, greedy, spec_on,
                               pad_token):
    """General path for batches with at least one sampling row.

    props (S, K) proposals drawn from qs (S, K, V), the draft's processed
    probabilities; plog (S, K+1, V) processed target logits; keys (S, W)
    raw PRNG keys; pos (S,) per-slot KV length; greedy / spec_on (S,)
    bool.  Greedy rows take the argmax accept/correct route (identical
    tokens to commit_speculative_greedy); sampling rows run the
    rejection-sampling scheme from the module docstring.  Returns the
    same tuple as commit_speculative_greedy.
    """
    s, k1, v = plog.shape
    k = k1 - 1
    pprob = jax.nn.softmax(plog, axis=-1)
    tops = jnp.argmax(plog, axis=-1).astype(jnp.int32)
    p_d = jnp.take_along_axis(pprob[:, :k], props[..., None],
                              axis=-1)[..., 0]              # p_i(x_i)
    q_d = jnp.take_along_axis(qs, props[..., None], axis=-1)[..., 0]
    kbase = jax.vmap(jax.random.fold_in)(keys, pos)
    u = jax.vmap(lambda kk: jax.random.uniform(
        jax.random.fold_in(kk, SALT_ACCEPT), (k,)))(kbase)
    # accept iff u < p/q, written u*q < p so q == 0 (a proposal the draft
    # could only produce with probability 0) rejects instead of dividing
    acc_sample = u * q_d < p_d
    acc_greedy = props == tops[:, :k]
    acc = jnp.where(greedy[:, None], acc_greedy, acc_sample) \
        & spec_on[:, None]
    n = _accept_count(acc)

    # correction token at the first disagreement (or the bonus position K
    # when everything was accepted): residual of p_n against q_n, with q
    # padded by a zero row so n == K uniformly yields p_K itself
    take_n = jnp.broadcast_to(n[:, None, None], (s, 1, v))
    p_n = jnp.take_along_axis(pprob, take_n, axis=1)[:, 0]   # (S, V)
    q_pad = jnp.concatenate([qs, jnp.zeros((s, 1, v), qs.dtype)], axis=1)
    q_n = jnp.take_along_axis(q_pad, take_n, axis=1)[:, 0]
    resid = jnp.clip(p_n - q_n, 0.0, None)
    tot = jnp.sum(resid, axis=-1, keepdims=True)
    # degenerate q == p (e.g. draft == target): the residual is empty and
    # the theorem says any draw works — fall back to p_n
    resid = jnp.where(tot > 0, resid / jnp.maximum(tot, 1e-38), p_n)
    kres = jax.vmap(lambda kk, nn: jax.random.fold_in(
        jax.random.fold_in(kk, SALT_RESIDUAL), nn))(kbase, n)
    corr_resid = jax.vmap(jax.random.categorical)(kres, jnp.log(resid))
    corr_greedy = jnp.take_along_axis(tops, n[:, None], axis=1)[:, 0]
    # spec-off sampling rows reproduce the plain decode step bit-exactly:
    # categorical(fold_in(key, pos), p_0) — same fold, same distribution
    corr_plain = jax.vmap(jax.random.categorical)(kbase, plog[:, 0])
    corr = jnp.where(greedy, corr_greedy,
                     jnp.where(spec_on, corr_resid,
                               corr_plain)).astype(jnp.int32)
    out, count, lp = _emit(props, plog, n, corr, pad_token)
    return out, count, n, corr, lp
