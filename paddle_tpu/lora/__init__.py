"""paddle_tpu.lora — batched LoRA adapters: train rank-r fine-tunes on a
frozen base, export them as sha-verified artifacts, and serve a
thousand of them on ONE base model with per-slot adapter ids as dynamic
inputs to the unchanged serving program family.

Train:   apply_lora(model, rank=8) -> train (only adapters move)
         export_adapter(model, "tenant_a.npz")
Serve:   eng = ServingEngine(model, lora=LoRAConfig(rank=8,
                                                    max_adapters=8))
         eng.load_adapter("tenant_a", "tenant_a.npz")
         eng.make_request(prompt, 32, adapter="tenant_a")
Fleet:   fleet.load_adapter("tenant_a", "tenant_a.npz")  # ships the
         artifact sha256-verified to every subprocess/remote worker
Gateway: Gateway(eng, tenants={"a": TenantConfig(adapter="tenant_a")})
"""
from .layers import (DEFAULT_TARGETS, LoRALinear, LoRAWrapper,  # noqa: F401
                     adapter_context, apply_lora, attach_serving_lora,
                     lora_keys)
from .train import (ADAPTER_VERSION, AdapterIntegrityError,  # noqa: F401
                    base_weights_hash, export_adapter, load_adapter,
                    read_adapter)
from .registry import (AdapterExhaustedError, AdapterNotFoundError,  # noqa: F401
                       AdapterRegistry, LoRAConfig)

__all__ = [
    "LoRALinear", "LoRAWrapper", "apply_lora", "DEFAULT_TARGETS",
    "lora_keys", "adapter_context", "attach_serving_lora",
    "export_adapter", "read_adapter", "load_adapter", "base_weights_hash",
    "ADAPTER_VERSION", "AdapterIntegrityError",
    "LoRAConfig", "AdapterRegistry", "AdapterNotFoundError",
    "AdapterExhaustedError",
]
