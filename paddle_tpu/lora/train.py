"""Adapter artifacts: export a trained LoRA model's factors as one
standalone npz, and read it back with integrity checks.

Artifact layout (single `.npz`, the `jit.save` convention of a json
header riding as a uint8 array):

    __header__        uint8 json: {version, rank, alpha, scaling,
                      targets, keys, base_sha, tensor_sha}
    {key}.A           fp32 [in_features, rank]     per wrapped layer
    {key}.B           fp32 [rank, out_features]

`tensor_sha` records the sha256 of every factor array's raw bytes — the
read side re-hashes and raises a typed `AdapterIntegrityError` on any
mismatch (a poisoned read can reject, never deliver garbage factors).
`base_sha` is the hash of the FROZEN base weights the adapter was
trained against; the serving registry refuses to apply an adapter to a
different base (unless the engine opted out for e.g. an int8-quantized
base — see `LoRAConfig.check_base_hash`).
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import tempfile
from typing import Dict, Tuple

import numpy as np

from ..core.errors import EnforceNotMet, InvalidArgumentError
from .layers import LoRALinear

__all__ = ["export_adapter", "read_adapter", "load_adapter",
           "base_weights_hash", "AdapterIntegrityError", "ADAPTER_VERSION"]

ADAPTER_VERSION = 1


class AdapterIntegrityError(EnforceNotMet, IOError):
    """Adapter artifact failed an integrity check (corrupt bytes, tensor
    sha mismatch, or base-weights-hash mismatch)."""
    code = "DataLoss"


def _sha(b: bytes) -> str:
    return hashlib.sha256(b).hexdigest()


def _arr_sha(a: np.ndarray) -> str:
    a = np.ascontiguousarray(a)
    return _sha(str(a.dtype).encode() + str(a.shape).encode()
                + a.tobytes())


def state_hash(state: Dict[str, "np.ndarray"]) -> str:
    """sha256 over a {key: array} state dict, excluding adapter factors
    and normalising away the `.base` hop LoRA wrappers introduce.  The
    digest equals `base_weights_hash` of a model carrying those arrays —
    `swap_weights` uses it to re-pin a live registry's expected base to
    the freshly-flipped weights without rebuilding anything."""
    items = []
    for k, v in state.items():
        leaf = k.rsplit(".", 1)[-1]
        if leaf in ("lora_A", "lora_B"):
            continue
        items.append((k.replace(".base.", "."), np.asarray(v)))
    h = hashlib.sha256()
    for k, a in sorted(items, key=lambda kv: kv[0]):
        h.update(k.encode())
        h.update(_arr_sha(a).encode())
    return h.hexdigest()


def base_weights_hash(model) -> str:
    """sha256 over the model's NON-adapter parameters+buffers.  Keys are
    normalised by stripping the `.base` hop LoRA wrappers introduce, so
    the hash of a LoRA-wrapped model equals the hash of the plain base
    model it was built from — the export/register handshake compares the
    two directly."""
    from ..jit import state_arrays
    return state_hash(state_arrays(model))


def _collect_factors(model) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    out = {}

    def walk(layer, prefix=""):
        for name, child in layer._sub_layers.items():
            if child is None:
                continue
            path = f"{prefix}.{name}" if prefix else name
            if isinstance(child, LoRALinear):
                out[path] = (np.asarray(child.lora_A._data, np.float32),
                             np.asarray(child.lora_B._data, np.float32))
            else:
                walk(child, path)
    walk(model)
    return out


def export_adapter(model, path: str, alpha=None) -> str:
    """Write the adapter factors of a LoRA-wrapped `model` to `path` as a
    standalone npz artifact and return the artifact's file sha256 (the
    handle the sha-verified ship channel and the registry cache key
    use)."""
    factors = _collect_factors(model)
    if not factors:
        raise InvalidArgumentError(
            "export_adapter: model has no LoRALinear layers — call "
            "lora.apply_lora(model, ...) and train first")
    ranks = {a.shape[1] for a, _ in factors.values()}
    if len(ranks) != 1:
        raise InvalidArgumentError(
            f"export_adapter: mixed ranks {sorted(ranks)} in one model")
    first = next(iter(_iter_lora(model)))
    header = {
        "version": ADAPTER_VERSION,
        "rank": int(first.rank),
        "alpha": float(first.alpha if alpha is None else alpha),
        "scaling": float(first.scaling),
        "targets": sorted({k.rsplit(".", 1)[-1] for k in factors}),
        "keys": sorted(factors),
        "base_sha": base_weights_hash(model),
        "tensor_sha": {},
    }
    payload = {}
    for k in sorted(factors):
        a, b = factors[k]
        payload[f"{k}.A"] = a
        payload[f"{k}.B"] = b
        header["tensor_sha"][f"{k}.A"] = _arr_sha(a)
        header["tensor_sha"][f"{k}.B"] = _arr_sha(b)
    payload["__header__"] = np.frombuffer(
        json.dumps(header, sort_keys=True).encode(), dtype=np.uint8).copy()
    # atomic publish: a reader (or a crashed exporter) must never see a
    # half-written artifact
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    with open(path, "rb") as f:
        return _sha(f.read())


def load_adapter(model, path: str):
    """Train-side restore: read a verified adapter artifact and assign its
    factors into the matching `LoRALinear` layers of an already-wrapped
    `model` (resume fine-tuning, or warm-start from another tenant).

    The model's wrapped key set must equal the artifact's `keys` and the
    ranks must match — mismatches are typed `InvalidArgumentError`s, not
    silent partial loads.  Returns the artifact header."""
    header, factors, _ = read_adapter(path)
    wrapped = {}

    def walk(layer, prefix=""):
        for name, child in layer._sub_layers.items():
            if child is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            if isinstance(child, LoRALinear):
                wrapped[p] = child
            else:
                walk(child, p)
    walk(model)
    if not wrapped:
        raise InvalidArgumentError(
            "load_adapter: model has no LoRALinear layers — call "
            "lora.apply_lora(model, rank=...) first")
    if sorted(wrapped) != header["keys"]:
        raise InvalidArgumentError(
            f"load_adapter: model wraps {sorted(wrapped)} but artifact "
            f"{path!r} carries {header['keys']}")
    for k, lyr in wrapped.items():
        a, b = factors[k]
        if a.shape[1] != lyr.rank:
            raise InvalidArgumentError(
                f"load_adapter: artifact rank {a.shape[1]} != model rank "
                f"{lyr.rank} at {k}")
        if (a.shape[0], b.shape[1]) != (lyr.in_features, lyr.out_features):
            raise InvalidArgumentError(
                f"load_adapter: factor shapes {a.shape}x{b.shape} do not "
                f"fit {k} ({lyr.in_features}->{lyr.out_features})")
        from ..core.tensor import Tensor
        lyr.lora_A._data = Tensor(a)._data
        lyr.lora_B._data = Tensor(b)._data
    return header


def _iter_lora(model):
    for layer in model.sublayers(include_self=True):
        if isinstance(layer, LoRALinear):
            yield layer


def read_adapter(path: str):
    """Load + verify an adapter artifact.  Returns `(header, factors,
    file_sha)` with `factors = {key: (A, B)}` as fp32 numpy arrays.

    The raw file bytes pass through the `adapter_corrupt` fault point
    (PDTPU_FAULT_ADAPTER_CORRUPT=n poisons the n-th read) BEFORE any
    verification, so an injected corruption is caught exactly where a
    real one would be: a typed `AdapterIntegrityError`, never silently
    garbage factors."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError as e:
        raise AdapterIntegrityError(
            f"adapter artifact {path!r} unreadable: {e}") from e
    from ..utils import faults
    raw = faults.maybe_corrupt_adapter_read(raw, path)
    file_sha = _sha(raw)
    try:
        z = np.load(io.BytesIO(raw), allow_pickle=False)
        header = json.loads(bytes(z["__header__"].tobytes()).decode())
        factors = {}
        for k in header["keys"]:
            factors[k] = (np.asarray(z[f"{k}.A"], np.float32),
                          np.asarray(z[f"{k}.B"], np.float32))
    except AdapterIntegrityError:
        raise
    except Exception as e:
        raise AdapterIntegrityError(
            f"adapter artifact {path!r} corrupt or malformed: "
            f"{type(e).__name__}: {e}") from e
    if header.get("version") != ADAPTER_VERSION:
        raise AdapterIntegrityError(
            f"adapter artifact {path!r}: version "
            f"{header.get('version')!r} != supported {ADAPTER_VERSION}")
    for k in header["keys"]:
        a, b = factors[k]
        for suffix, arr in ((f"{k}.A", a), (f"{k}.B", b)):
            want = header["tensor_sha"].get(suffix)
            got = _arr_sha(arr)
            if want != got:
                raise AdapterIntegrityError(
                    f"adapter artifact {path!r}: tensor {suffix} sha256 "
                    f"mismatch (recorded {want}, recomputed {got}) — "
                    "refusing to load garbage factors; re-ship the "
                    "artifact")
    return header, factors, file_sha
