"""AdapterRegistry: page LoRA factor stacks in and out of a FIXED device
buffer, the way the paged KV pool manages blocks.

The registry owns, per wrapped layer key, two device stacks

    A[max_adapters + 1, in_features, rank]
    B[max_adapters + 1, rank, out_features]

plus one `scale[max_adapters + 1]` vector.  Slot 0 is permanently the
base model: all-zero factors with scale 0, so adapter id 0 is
bit-identical to running without LoRA.  Slots 1..max_adapters hold
loaded adapters; when all are occupied a new `register()` evicts the
least-recently-used slot with ZERO active references (requests pin
their adapter from admission to release), and if every slot is pinned
it raises the typed `AdapterExhaustedError` backpressure signal instead
of blocking.

Page-in never compiles after construction: slot writes go through ONE
jitted scatter per distinct stack shape, traced eagerly at
construction with the out-of-bounds sentinel index (`mode="drop"` makes
the warmup write a no-op) — the `_cow_fn` precompile pattern from the
paged KV pool.  The writer deliberately does NOT donate its input:
`load_adapter` runs on a command/RPC thread while the engine loop may
hold references from an earlier `device_args()`, and donating would
delete those buffers under a launching decode call.  The copy is
O(stacks) per page-in — cheap, rare, and race-free.
The stacks are ordinary arguments of the serving
programs (`device_args()`), NOT engine state: the `state_dict()` key
set, `swap_weights` validation and the run-transfer codec are
untouched.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import (EnforceNotMet, InvalidArgumentError,
                           NotFoundError, ResourceExhaustedError)
from ..utils.monitor import stat_add
from .layers import DEFAULT_TARGETS
from .train import AdapterIntegrityError, read_adapter

__all__ = ["LoRAConfig", "AdapterRegistry", "AdapterNotFoundError",
           "AdapterExhaustedError", "AdapterIntegrityError"]


class AdapterNotFoundError(NotFoundError):
    """No adapter with that name is loaded in the registry (terminal
    typed rejection — a consumer must never hang on an unknown
    adapter)."""
    code = "NotFound"


class AdapterExhaustedError(ResourceExhaustedError):
    """Every adapter slot is pinned by in-flight requests — typed
    backpressure, retry after traffic drains."""
    code = "ResourceExhausted"


class LoRAConfig:
    """Serve-side LoRA configuration for `ServingEngine(lora=...)`.

    rank             factor rank every loadable adapter must match
    max_adapters     loadable slots (the device buffer holds
                     max_adapters + 1 stacks; slot 0 is the base model)
    targets          attribute names to wrap (GPTBlock projections by
                     default)
    check_base_hash  verify each artifact's recorded base-weights hash
                     against this engine's base model.  Set False when
                     the serving base differs from the training base by
                     construction — e.g. int8 weight-only quantization
                     (adapters stay fp32 on top of the int8 base).
    base_sha         expected base hash override (defaults to hashing
                     the engine's model at registry construction).
    """

    __slots__ = ("rank", "max_adapters", "targets", "check_base_hash",
                 "base_sha")

    def __init__(self, rank: int = 8, max_adapters: int = 8,
                 targets: Sequence[str] = DEFAULT_TARGETS,
                 check_base_hash: bool = True,
                 base_sha: Optional[str] = None):
        if rank <= 0:
            raise InvalidArgumentError(f"LoRA rank must be positive, "
                                       f"got {rank}")
        if max_adapters <= 0:
            raise InvalidArgumentError(
                f"max_adapters must be positive, got {max_adapters}")
        self.rank = int(rank)
        self.max_adapters = int(max_adapters)
        self.targets = tuple(targets)
        self.check_base_hash = bool(check_base_hash)
        self.base_sha = base_sha

    def spec(self) -> dict:
        """json-portable form (worker boot specs, program-set
        manifests)."""
        return {"rank": self.rank, "max_adapters": self.max_adapters,
                "targets": list(self.targets),
                "check_base_hash": self.check_base_hash,
                "base_sha": self.base_sha}

    @classmethod
    def from_spec(cls, spec: dict) -> "LoRAConfig":
        return cls(rank=spec.get("rank", 8),
                   max_adapters=spec.get("max_adapters", 8),
                   targets=tuple(spec.get("targets", DEFAULT_TARGETS)),
                   check_base_hash=spec.get("check_base_hash", True),
                   base_sha=spec.get("base_sha"))


class _Slot:
    __slots__ = ("name", "refs", "tick", "file_sha")

    def __init__(self):
        self.name = None
        self.refs = 0
        self.tick = 0
        self.file_sha = None


class AdapterRegistry:
    """Thread-safe adapter slot manager + device factor stacks."""

    def __init__(self, cfg: LoRAConfig,
                 shapes: Dict[str, Tuple[int, int]],
                 base_sha: Optional[str] = None):
        self.cfg = cfg
        self.keys = tuple(sorted(shapes))
        self.base_sha = cfg.base_sha or base_sha
        self._lock = threading.RLock()
        self._tick = 0
        m = cfg.max_adapters + 1
        self._A = {}
        self._B = {}
        for k in self.keys:
            in_f, out_f = shapes[k]
            self._A[k] = jnp.zeros((m, in_f, cfg.rank), jnp.float32)
            self._B[k] = jnp.zeros((m, cfg.rank, out_f), jnp.float32)
        self._scales = jnp.zeros((m,), jnp.float32)
        self._slots = [_Slot() for _ in range(m)]
        self._slots[0].name = "<base>"
        self._slots[0].refs = 1  # the base slot is never evictable
        self._by_name: Dict[str, int] = {}
        self._evictions = 0
        self._loads = 0
        # one jitted slot-writer per distinct (stack, row) aval pair
        # (jax.jit caches by aval); warmed NOW with the sentinel index
        # so a live page-in never traces — the post-warmup zero-compile
        # contract extends to adapter hot-load.  No donation: see the
        # module docstring (thread-safety vs. the engine loop's
        # device_args() references).
        self._write = jax.jit(
            lambda stack, idx, row: stack.at[idx].set(row, mode="drop"))
        sent = jnp.int32(m)
        for k in self.keys:
            self._A[k] = self._write(
                self._A[k], sent, jnp.zeros(self._A[k].shape[1:],
                                            jnp.float32))
            self._B[k] = self._write(
                self._B[k], sent, jnp.zeros(self._B[k].shape[1:],
                                            jnp.float32))
        self._scales = self._write(self._scales, sent, jnp.float32(0.0))
        self._publish_gauge()

    # -- device-side views -------------------------------------------------
    def device_args(self):
        """The lora program-argument pytree: ((A,B) per key in `self.keys`
        order, scales).  Passed to every prefill/decode call; the program
        body rebuilds the {key: (A,B)} dict zip'd with the engine's
        static key tuple."""
        with self._lock:
            return (tuple((self._A[k], self._B[k]) for k in self.keys),
                    self._scales)

    # -- lifecycle ---------------------------------------------------------
    def register(self, name: str, path: str) -> int:
        """Load an adapter artifact into a slot under `name`; returns the
        slot index (the adapter id).  Idempotent for the same artifact
        bytes (matching file sha re-uses the existing slot — the
        zero-byte re-attach path).  Raises typed errors:
        `AdapterIntegrityError` (corrupt artifact / wrong base),
        `InvalidArgumentError` (rank/targets mismatch),
        `AdapterExhaustedError` (all slots pinned)."""
        if not name or name == "<base>":
            raise InvalidArgumentError(
                f"invalid adapter name {name!r}")
        header, factors, file_sha = read_adapter(path)
        if header["rank"] != self.cfg.rank:
            raise InvalidArgumentError(
                f"adapter {name!r} has rank {header['rank']}, engine "
                f"was built with LoRAConfig(rank={self.cfg.rank}) — "
                "ranks are baked into the compiled programs")
        if sorted(header["keys"]) != list(self.keys):
            raise InvalidArgumentError(
                f"adapter {name!r} wraps {sorted(header['keys'])} but "
                f"the engine wraps {list(self.keys)} "
                f"(LoRAConfig(targets={list(self.cfg.targets)}))")
        if (self.cfg.check_base_hash and self.base_sha is not None
                and header.get("base_sha") != self.base_sha):
            raise AdapterIntegrityError(
                f"adapter {name!r} was trained against base weights "
                f"{header.get('base_sha', '?')[:12]}..., this engine "
                f"serves base {self.base_sha[:12]}... — refusing to "
                "apply a mismatched adapter (pass LoRAConfig("
                "check_base_hash=False) only for deliberate base "
                "transforms like int8 quantization)")
        with self._lock:
            idx = self._by_name.get(name)
            if idx is not None and self._slots[idx].file_sha == file_sha:
                self._slots[idx].tick = self._bump()
                return idx
            if idx is None:
                idx = self._alloc(name)
            slot = self._slots[idx]
            slot.name = name
            slot.file_sha = file_sha
            slot.tick = self._bump()
            self._by_name[name] = idx
            i = jnp.int32(idx)
            for k in self.keys:
                a, b = factors[k]
                if (a.shape != self._A[k].shape[1:]
                        or b.shape != self._B[k].shape[1:]):
                    raise InvalidArgumentError(
                        f"adapter {name!r} factor shapes for {k} "
                        f"({a.shape}/{b.shape}) do not match the engine "
                        f"({self._A[k].shape[1:]}/{self._B[k].shape[1:]})")
                self._A[k] = self._write(self._A[k], i, jnp.asarray(a))
                self._B[k] = self._write(self._B[k], i, jnp.asarray(b))
            self._scales = self._write(
                self._scales, i, jnp.float32(header["scaling"]))
            self._loads += 1
            stat_add("STAT_lora_adapter_loads")
            self._publish_gauge()
            return idx

    def _alloc(self, name: str) -> int:
        for i in range(1, len(self._slots)):
            if self._slots[i].name is None:
                return i
        victim, oldest = None, None
        for i in range(1, len(self._slots)):
            s = self._slots[i]
            if s.refs == 0 and (oldest is None or s.tick < oldest):
                victim, oldest = i, s.tick
        if victim is None:
            raise AdapterExhaustedError(
                f"all {self.cfg.max_adapters} adapter slots are pinned "
                f"by in-flight requests; cannot load {name!r} — retry "
                "after traffic drains or raise LoRAConfig(max_adapters=)")
        old = self._slots[victim]
        self._by_name.pop(old.name, None)
        old.file_sha = None
        self._evictions += 1
        stat_add("STAT_lora_adapter_evictions")
        return victim

    def _bump(self) -> int:
        self._tick += 1
        return self._tick

    # -- request pinning ---------------------------------------------------
    def resolve(self, name: Optional[str]) -> int:
        """Name -> adapter id WITHOUT pinning (admission-time lookup,
        `make_request` validation).  None/'' means the base model."""
        if not name:
            return 0
        with self._lock:
            idx = self._by_name.get(name)
            if idx is None:
                raise AdapterNotFoundError(
                    f"adapter {name!r} is not loaded on this engine "
                    f"(loaded: {sorted(self._by_name) or 'none'}) — "
                    "register it first (engine.load_adapter / "
                    "fleet.load_adapter)")
            return idx

    def acquire(self, name: Optional[str]) -> int:
        """resolve + pin: the slot cannot be evicted until `release`."""
        if not name:
            return 0
        with self._lock:
            idx = self.resolve(name)
            self._slots[idx].refs += 1
            self._slots[idx].tick = self._bump()
            return idx

    def release(self, idx: int):
        if idx <= 0:
            return
        with self._lock:
            s = self._slots[idx]
            if s.refs > 0:
                s.refs -= 1

    # -- introspection -----------------------------------------------------
    def loaded(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._by_name)

    def file_sha(self, idx: int) -> Optional[str]:
        """sha256 of the artifact resident in slot `idx` (the fleet's
        zero-byte re-attach cache key)."""
        with self._lock:
            return self._slots[idx].file_sha

    def shas(self) -> Dict[str, str]:
        """name -> artifact sha256 of every resident adapter.  Cheap on
        purpose: health snapshots poll this per replica per tick."""
        with self._lock:
            return {n: self._slots[i].file_sha
                    for n, i in sorted(self._by_name.items())}

    def stats(self) -> dict:
        with self._lock:
            return {
                "rank": self.cfg.rank,
                "max_adapters": self.cfg.max_adapters,
                "loaded": len(self._by_name),
                "pinned": sum(1 for s in self._slots[1:] if s.refs > 0),
                "loads": self._loads,
                "evictions": self._evictions,
                "adapters": sorted(self._by_name),
                "shas": {n: self._slots[i].file_sha
                         for n, i in sorted(self._by_name.items())},
            }

    def _publish_gauge(self):
        try:
            from ..observability import gauge
            gauge("lora_adapters_loaded",
                  help="adapters resident in the registry").set(
                      len(self._by_name))
        except Exception:
            pass
