"""LoRA adapter layers: the train-side wrapper and the serve-side
batched shim.

Two distinct classes on purpose:

- `LoRALinear` (train) owns REAL rank-r parameters (`lora_A`, `lora_B`)
  registered on the layer, so they flow through `state_dict()`,
  `jit.TrainStep` (which selects trainables by the `trainable` flag),
  checkpointing and `recompute_policy` like any other parameter.  The
  wrapped base layer's parameters are frozen (`trainable=False` +
  `stop_gradient`) — `apply_lora` freezes the WHOLE model first, so
  only adapter factors move under training.

- the serve side does NO model surgery at all: `attach_serving_lora`
  installs a forward POST-HOOK on each target linear that adds
  `scale[aid] * (x @ A[aid]) @ B[aid]` to the layer's output.  The
  factor STACKS `[max_adapters+1, ...]` arrive at trace time through a
  thread-local `adapter_context` — they are ordinary program arguments
  of the serving programs and the adapter id is a per-slot dynamic
  input, so heterogeneous adapters batch inside ONE compiled
  decode/verify program (the PR-4 per-slot dynamic-sampling pattern).
  Slot 0 of every stack is all-zero with scale 0: adapter id 0 is the
  base model, bit-identical (`y + 0.0*(...)`) to a no-LoRA engine.
  Because the hook registers no parameters, buffers or sublayers, the
  engine's `state_dict()` key set — and with it `swap_weights`
  validation, weight refresh, `engine_config_hash` and the
  run-transfer codec — is byte-for-byte unchanged.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor, unwrap
from ..nn.layer_base import Layer
from ..nn import initializer as I

__all__ = ["LoRALinear", "LoRAWrapper", "apply_lora", "DEFAULT_TARGETS",
           "adapter_context", "attach_serving_lora", "lora_keys"]

# GPTBlock's four projection Linears — the default adaptation surface.
# Targets are matched by ATTRIBUTE NAME anywhere in the layer tree, so
# the same tuple works for any stack of blocks.
DEFAULT_TARGETS = ("qkv", "proj", "ffn_in", "ffn_out")


def _linear_like(layer) -> bool:
    """Anything with in/out feature counts and a callable forward can be
    LoRA-wrapped — covers `nn.Linear` AND `quantization.
    Int8WeightOnlyLinear` (int8 base weights compose; the adapter factors
    stay fp32)."""
    return (hasattr(layer, "in_features") and hasattr(layer, "out_features")
            and isinstance(layer, Layer))


class LoRALinear(Layer):
    """Frozen base + trainable rank-r update: `y = base(x) +
    (alpha/rank) * (x @ A) @ B`.

    `A` is Normal(0, 1/rank)-initialised, `B` starts at zero — the
    wrapped layer is EXACTLY the base layer at step 0, so wrapping never
    perturbs a pretrained model until training moves `B`.
    """

    def __init__(self, base, rank: int = 8, alpha: Optional[float] = None):
        super().__init__()
        if not _linear_like(base):
            raise TypeError(
                f"LoRALinear needs a linear-like base layer with "
                f"in_features/out_features, got {type(base).__name__}")
        if rank <= 0:
            raise ValueError(f"LoRA rank must be positive, got {rank}")
        self.rank = int(rank)
        self.alpha = float(alpha if alpha is not None else 2 * rank)
        self.scaling = self.alpha / self.rank
        self.base = base
        for p in base.parameters():
            p.trainable = False
            p.stop_gradient = True
        in_f, out_f = int(base.in_features), int(base.out_features)
        self.in_features, self.out_features = in_f, out_f
        self.lora_A = self.create_parameter(
            (in_f, self.rank), default_initializer=I.Normal(0.0, 1.0 / rank))
        self.lora_B = self.create_parameter(
            (self.rank, out_f), default_initializer=I.Constant(0.0))

    def forward(self, x):
        # traced ops, not raw jnp: the delta path must record tape nodes
        # so d(loss)/d(lora_A|lora_B) flows while the base stays frozen
        from ..tensor.linalg import matmul
        y = self.base(x)
        delta = matmul(matmul(x, self.lora_A), self.lora_B) * self.scaling
        return y + delta

    def merged_weight(self):
        """The dense-equivalent weight `W + scaling * A @ B` (test oracle
        and offline-merge export)."""
        return (unwrap(self.base.weight)
                + self.scaling * (unwrap(self.lora_A) @ unwrap(self.lora_B)))

    def extra_repr(self):
        return (f"rank={self.rank}, alpha={self.alpha}, "
                f"base={type(self.base).__name__}")


def _walk_targets(model: Layer, targets: Sequence[str], prefix=""):
    """Yield (parent, attr_name, dotted_path, child) for every
    linear-like child whose attribute name is in `targets`, in layer-tree
    order (deterministic: OrderedDict)."""
    for name, child in list(model._sub_layers.items()):
        if child is None:
            continue
        path = f"{prefix}.{name}" if prefix else name
        if name in targets and _linear_like(child):
            yield model, name, path, child
        else:
            yield from _walk_targets(child, targets, path)


def apply_lora(model: Layer, rank: int = 8, alpha: Optional[float] = None,
               targets: Sequence[str] = DEFAULT_TARGETS):
    """In-place LoRA conversion for TRAINING: freezes every parameter of
    `model`, then swaps each target Linear for a `LoRALinear` wrapper
    whose rank-r factors are the only trainables left.  Returns the list
    of wrapped dotted paths (the adapter's key set).  Composes with
    `TrainStep(accum_steps=)`, `jit.recompute_policy` and guarded steps
    exactly like any other model surgery — the swap goes through
    `setattr` so attribute-style forwards see the wrapper."""
    for p in model.parameters():
        p.trainable = False
        p.stop_gradient = True
    wrapped = []
    for parent, name, path, child in _walk_targets(model, tuple(targets)):
        if isinstance(child, LoRALinear):
            continue
        setattr(parent, name, LoRALinear(child, rank=rank, alpha=alpha))
        wrapped.append(path)
    if not wrapped:
        raise ValueError(
            f"apply_lora found no linear-like layers named {tuple(targets)} "
            f"in {type(model).__name__}")
    return wrapped


class LoRAWrapper(Layer):
    """Model-level LoRA handle: wraps `model` in place via `apply_lora`
    and keeps the train->export lifecycle in one object.

        w = LoRAWrapper(model, rank=8)      # freezes base, wraps targets
        loss = w(ids).mean(); loss.backward()   # only factors move
        sha = w.export("tenant_a.npz")      # adapter-only artifact

    The wrapper is a thin Layer over the SAME (mutated) model — the
    underlying module keeps working wherever it is already referenced,
    and `state_dict`/`TrainStep`/checkpointing see the wrapped model's
    parameters through the `model` sublayer hop.
    """

    def __init__(self, model: Layer, rank: int = 8,
                 alpha: Optional[float] = None,
                 targets: Sequence[str] = DEFAULT_TARGETS):
        super().__init__()
        self.paths = apply_lora(model, rank=rank, alpha=alpha,
                                targets=targets)
        self.model = model
        self.rank = int(rank)
        self.targets = tuple(targets)

    def forward(self, *args, **kwargs):
        return self.model(*args, **kwargs)

    def trainable_parameters(self):
        """Only the rank-r factors — everything else is frozen."""
        return [p for p in self.model.parameters() if p.trainable]

    def export(self, path: str, alpha=None) -> str:
        """Write the adapter-only npz artifact; returns its file sha256."""
        from .train import export_adapter
        return export_adapter(self.model, path, alpha=alpha)

    def load(self, path: str):
        """Restore previously exported factors into this wrapper (resume
        fine-tuning from an adapter artifact)."""
        from .train import load_adapter
        return load_adapter(self.model, path)

    def extra_repr(self):
        return f"rank={self.rank}, wrapped={len(self.paths)}"


# ---------------------------------------------------------------------------
# serving: batched adapter shim + trace-time context
# ---------------------------------------------------------------------------

_TLS = threading.local()


class adapter_context:
    """Trace-time context supplying the factor stacks and the (traced)
    adapter id to every `_BatchedLoRALinear` reached by the forward.
    Entered INSIDE program bodies — per vmapped row for decode/verify,
    once with a scalar id for prefill — so the values are tracers and the
    context only exists while the program is being traced."""

    __slots__ = ("stacks", "scales", "aid", "_prev")

    def __init__(self, stacks: Dict[str, Tuple], scales, aid):
        self.stacks = stacks
        self.scales = scales
        self.aid = aid
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_TLS, "ctx", None)
        _TLS.ctx = self
        return self

    def __exit__(self, *exc):
        _TLS.ctx = self._prev
        return False


def _current_ctx():
    return getattr(_TLS, "ctx", None)


def _lora_post_hook(lora_key: str):
    """The serving delta as a forward post-hook: outside any
    `adapter_context` the layer is the base verbatim (warmup paths that
    never enter a context, any non-serving use of the model); inside
    one, the per-adapter factors are gathered by the (traced) adapter
    id and added to the layer's output."""

    def hook(layer, inputs, output):
        ctx = _current_ctx()
        if ctx is None:
            return None
        A, B = ctx.stacks[lora_key]
        a = jnp.take(A, ctx.aid, axis=0, mode="clip")
        b = jnp.take(B, ctx.aid, axis=0, mode="clip")
        s = jnp.take(ctx.scales, ctx.aid, mode="clip")
        xr = unwrap(inputs[0])
        delta = ((xr @ a) @ b) * s
        return output + Tensor(delta)
    return hook


def attach_serving_lora(model: Layer,
                        targets: Sequence[str] = DEFAULT_TARGETS):
    """Arm `model` for batched multi-adapter serving: installs the LoRA
    forward post-hook on every target linear.  NO model surgery — no new
    parameters, buffers or sublayers — so `state_dict()` keys,
    `swap_weights` validation and weight-refresh artifacts are untouched
    (int8-quantized bases hook identically: the adapter delta stays
    fp32 on top of the int8 matmul).  Returns {dotted_path:
    (in_features, out_features)} — the registry sizes its device stacks
    from this.  Rejects a model that is already armed or train-wrapped
    (double-hooking would double-apply adapters)."""
    shapes = {}
    for parent, name, path, child in _walk_targets(model, tuple(targets)):
        if isinstance(child, LoRALinear):
            raise ValueError(
                f"layer {path} is a train-side LoRALinear; serve either "
                "the merged model or the base model + exported adapter, "
                "not the training wrapper")
        if getattr(child, "_lora_serving_key", None) is not None:
            raise ValueError(f"layer {path} already has serving LoRA "
                             "attached")
        child._lora_serving_key = path
        child.register_forward_post_hook(_lora_post_hook(path))
        shapes[path] = (int(child.in_features), int(child.out_features))
    if not shapes:
        raise ValueError(
            f"no linear-like layers named {tuple(targets)} found in "
            f"{type(model).__name__}")
    return shapes


def lora_keys(model: Layer):
    """Sorted dotted paths of LoRA-wrapped layers (train or serve)."""
    keys = []

    def walk(layer, prefix=""):
        for name, child in layer._sub_layers.items():
            if child is None:
                continue
            path = f"{prefix}.{name}" if prefix else name
            if (isinstance(child, LoRALinear)
                    or getattr(child, "_lora_serving_key", None)):
                keys.append(path)
            else:
                walk(child, path)
    walk(model)
    return sorted(keys)
