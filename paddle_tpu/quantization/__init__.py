"""Quantization: QAT fake-quant training + post-training quantization.

Reference: the slim quantization subsystem —
python/paddle/fluid/contrib/slim/quantization/imperative/qat.py:53
(ImperativeQuantAware: replace Linear/Conv2D with fake-quant wrappers),
post_training_quantization.py:1 (PTQ: sample activation ranges with
observers, then bake int8 weights), quantization_pass.py:1 (the
fake_quantize/dequantize op family: abs_max, channel_wise_abs_max,
moving_average_abs_max).

TPU-native design: fake-quant is a pure jnp transform trained through a
straight-through estimator (`x + stop_grad(qdq(x) - x)`) so QAT runs
through XLA like any other op; activation observers are abs-max reductions
held as Layer buffers; a converted model stores int8 weight arrays plus
per-channel f32 scales and dequantizes at the matmul input, where XLA
fuses the rescale into the dot — int8 halves/quarters the HBM weight
footprint (the TPU win); the MXU still computes in bf16/f32.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, unwrap
from ..nn.layer_base import Layer
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from ..nn import functional as F

__all__ = [
    "fake_quantize_dequantize_abs_max",
    "fake_quantize_dequantize_channel_wise_abs_max",
    "QuantedLinear", "QuantedConv2D",
    "ImperativeQuantAware", "PostTrainingQuantization",
    "Int8Linear", "Int8Conv2D",
    # serving int8 weight-only path (dequant-at-use inside the compiled
    # serving/generate programs)
    "quantize_weight_int8", "Int8WeightOnlyLinear", "quantize_for_serving",
]


def _qmax(bits: int) -> float:
    return float(2 ** (bits - 1) - 1)


def _qdq(x, scale, qmax):
    """Quantize-dequantize a Tensor given a (broadcastable) scale."""
    s = scale / qmax
    return (x / s).round().clip(-qmax, qmax) * s


def fake_quantize_dequantize_abs_max(x, bits=8):
    """Per-tensor abs-max fake quant with STE (reference:
    quantization_pass.py fake_quantize_dequantize_abs_max)."""
    qmax = _qmax(bits)
    scale = x.abs().max().clip(min=1e-8).detach()
    return x + (_qdq(x, scale, qmax) - x).detach()


def fake_quantize_dequantize_channel_wise_abs_max(w, quant_axis=0, bits=8):
    """Per-channel abs-max fake quant with STE (reference:
    quantization_pass.py channel_wise_abs_max)."""
    qmax = _qmax(bits)
    axes = tuple(i for i in range(len(w.shape)) if i != quant_axis)
    scale = w.abs().max(axis=axes, keepdim=True).clip(min=1e-8).detach()
    return w + (_qdq(w, scale, qmax) - w).detach()


def _qdq_with_scale(x, scale_value, bits):
    """Fake quant with an EXTERNAL scalar scale (moving-average path).
    A never-observed scale (== 0, e.g. eval before any training step) is an
    identity — quantizing against the epsilon floor would saturate every
    activation to ~1e-8 garbage."""
    raw = unwrap(scale_value)
    qmax = _qmax(bits)
    scale = Tensor(jnp.maximum(raw, 1e-8), stop_gradient=True)
    q = _qdq(x, scale, qmax)
    observed = Tensor(jnp.asarray(raw > 0), stop_gradient=True)
    from ..tensor.search import where
    return x + (where(observed, q, x) - x).detach()


class _QuantedBase(Layer):
    """Shared QAT machinery: channel-wise weight fake quant + a
    moving-average abs-max activation observer buffer."""

    def _init_quant(self, weight_bits, activation_bits, moving_rate,
                    weight_quantize_type):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._moving_rate = moving_rate
        self._wq_type = weight_quantize_type
        self.register_buffer("act_scale",
                             Tensor(jnp.zeros((), jnp.float32),
                                    stop_gradient=True))

    def _quant_weight(self, w, channel_axis):
        if self._wq_type == "abs_max":  # per-tensor scale
            return fake_quantize_dequantize_abs_max(w, self._weight_bits)
        return fake_quantize_dequantize_channel_wise_abs_max(
            w, quant_axis=channel_axis, bits=self._weight_bits)

    def _observe_and_quant_act(self, x):
        """Update the moving-average abs-max (training) and fake-quant x.
        Buffer updates are eager-side effects — QAT is a dygraph training
        flow (reference: ImperativeQuantAware is imperative-only too)."""
        if self.training:
            cur = jnp.max(jnp.abs(unwrap(x))).astype(jnp.float32)
            r = self._moving_rate
            state = unwrap(self.act_scale)
            accum = jnp.where(state > 0, state * r + cur * (1 - r), cur)
            # buffer registry update (plain attr assignment would shadow the
            # buffer and leave state_dict stale)
            self._buffers["act_scale"] = Tensor(accum, stop_gradient=True)
        return _qdq_with_scale(x, unwrap(self.act_scale),
                               self._activation_bits)


class QuantedLinear(_QuantedBase):
    """QAT wrapper around Linear (reference: imperative/quant_layers.py
    QuantizedLinear).  Shares the wrapped layer's Parameters, so existing
    optimizers keep updating the same tensors."""

    def __init__(self, layer: Linear, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._layer = layer
        self._init_quant(weight_bits, activation_bits, moving_rate,
                         weight_quantize_type)

    def forward(self, x):
        x = self._observe_and_quant_act(x)
        # Linear weight is (in, out): the output channel is axis 1
        w = self._quant_weight(self._layer.weight, channel_axis=1)
        return F.linear(x, w, self._layer.bias)


class QuantedConv2D(_QuantedBase):
    """QAT wrapper around Conv2D (reference: imperative/quant_layers.py
    QuantizedConv2D)."""

    def __init__(self, layer: Conv2D, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type="channel_wise_abs_max"):
        super().__init__()
        self._layer = layer
        self._init_quant(weight_bits, activation_bits, moving_rate,
                         weight_quantize_type)

    def forward(self, x):
        x = self._observe_and_quant_act(x)
        lay = self._layer
        w = self._quant_weight(lay.weight, channel_axis=0)
        return F.conv2d(x, w, lay.bias, lay.stride, lay.padding,
                        lay.dilation, lay.groups, lay.data_format)


def _replace_layers(model: Layer, factory):
    """Walk the layer tree and swap quantizable leaves via factory(child)
    -> replacement | None.  Goes through setattr: Layer.__setattr__ caches
    sublayers in the instance __dict__ too, and a bare _sub_layers update
    would leave attribute-style models (`self.fc = Linear(...)`) silently
    executing the original fp32 layer."""
    for name, child in list(model._sub_layers.items()):
        repl = factory(child)
        if repl is not None:
            setattr(model, name, repl)
        else:
            _replace_layers(child, factory)
    return model


class ImperativeQuantAware:
    """Dygraph QAT driver (reference: imperative/qat.py:53).

    quantize(model) swaps every Linear/Conv2D for its fake-quant wrapper
    in place; train as usual; save_quantized_model exports through
    jit.save with the qdq ops baked into the traced program."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 moving_rate=0.9, quantizable_layer_type=None):
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(weight_quantize_type)
        if activation_quantize_type != "moving_average_abs_max":
            raise ValueError(activation_quantize_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self._wq_type = weight_quantize_type
        self._types = tuple(quantizable_layer_type or (Linear, Conv2D))

    def quantize(self, model: Layer) -> Layer:
        def factory(child):
            if isinstance(child, Linear) and Linear in self._types:
                return QuantedLinear(child, self._wbits, self._abits,
                                     self._rate, self._wq_type)
            if isinstance(child, Conv2D) and Conv2D in self._types:
                return QuantedConv2D(child, self._wbits, self._abits,
                                     self._rate, self._wq_type)
            return None
        return _replace_layers(model, factory)

    def save_quantized_model(self, layer, path, input_spec=None):
        from .. import jit
        layer.eval()
        jit.save(layer, path, input_spec=input_spec)


# ---------------------------------------------------------------------------
# post-training quantization


class Int8Linear(Layer):
    """Converted Linear: int8 weight + per-out-channel scale, dequantized
    at the input of the dot (XLA fuses the rescale into the matmul)."""

    def __init__(self, layer: Linear, bits=8, act_scale=None, act_bits=8):
        super().__init__()
        if bits > 8:
            raise ValueError(
                f"int8 storage holds at most 8-bit weights, got bits={bits}")
        qmax = _qmax(bits)
        w = unwrap(layer.weight)
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=0, keepdims=True), 1e-8)
        self.register_buffer("w_int8", Tensor(
            jnp.clip(jnp.round(w / scale * qmax), -qmax, qmax).astype(
                jnp.int8), stop_gradient=True))
        self.register_buffer("w_scale", Tensor(
            (scale / qmax).astype(jnp.float32), stop_gradient=True))
        self.bias = layer.bias
        self._act_scale = act_scale
        self._act_bits = act_bits

    def forward(self, x):
        if self._act_scale is not None:  # static activation quant
            x = _qdq_with_scale(x, self._act_scale, self._act_bits)
        w = Tensor(unwrap(self.w_int8).astype(jnp.float32)
                   * unwrap(self.w_scale), stop_gradient=True)
        return F.linear(x, w, self.bias)


class Int8Conv2D(Layer):
    """Converted Conv2D: int8 weight + per-out-channel scale."""

    def __init__(self, layer: Conv2D, bits=8, act_scale=None, act_bits=8):
        super().__init__()
        if bits > 8:
            raise ValueError(
                f"int8 storage holds at most 8-bit weights, got bits={bits}")
        qmax = _qmax(bits)
        w = unwrap(layer.weight)
        scale = jnp.maximum(
            jnp.max(jnp.abs(w), axis=(1, 2, 3), keepdims=True), 1e-8)
        self.register_buffer("w_int8", Tensor(
            jnp.clip(jnp.round(w / scale * qmax), -qmax, qmax).astype(
                jnp.int8), stop_gradient=True))
        self.register_buffer("w_scale", Tensor(
            (scale / qmax).astype(jnp.float32), stop_gradient=True))
        self.bias = layer.bias
        self._cfg = (layer.stride, layer.padding, layer.dilation,
                     layer.groups, layer.data_format)
        self._act_scale = act_scale
        self._act_bits = act_bits

    def forward(self, x):
        if self._act_scale is not None:
            x = _qdq_with_scale(x, self._act_scale, self._act_bits)
        w = Tensor(unwrap(self.w_int8).astype(jnp.float32)
                   * unwrap(self.w_scale), stop_gradient=True)
        stride, padding, dilation, groups, fmt = self._cfg
        return F.conv2d(x, w, self.bias, stride, padding, dilation, groups,
                        fmt)


def quantize_weight_int8(w, per_channel=True, axis=1):
    """Symmetric abs-max int8 quantization of a raw weight array.

    Returns ``(w_int8, scale)`` with ``w ≈ w_int8 * scale`` — scale is the
    DEQUANT multiplier (absmax/127), shaped to broadcast: per-channel over
    ``axis`` keeps one scale per output channel (for a (in, out) Linear
    weight, axis=1 -> scale (1, out)); per_channel=False collapses to one
    scalar scale shaped (1,) * ndim.  Round-trip error is bounded by
    scale/2 per element — the `test_quantization` round-trip bound."""
    w = jnp.asarray(w)
    if per_channel:
        axes = tuple(i for i in range(w.ndim) if i != axis)
        absmax = jnp.max(jnp.abs(w), axis=axes, keepdims=True)
    else:
        absmax = jnp.max(jnp.abs(w)).reshape((1,) * w.ndim)
    absmax = jnp.maximum(absmax, 1e-8)
    scale = (absmax / 127.0).astype(jnp.float32)
    w_int8 = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_int8, scale


class Int8WeightOnlyLinear(Layer):
    """Serving int8 weight-only Linear: the weight lives as an int8
    buffer + per-out-channel fp32 scale and is dequantized AT USE via
    `ops.int8_matmul.dequant_matmul` (pallas kernel on TPU, XLA-fused jnp
    fallback elsewhere).  Unlike `Int8Linear` there is NO activation
    quantization: the decode path is weight-HBM-bound, activations stay
    floating point, so the only error source is the ~1/127 per-channel
    weight grid.  Buffers ride through `jit.state_arrays` into every
    compiled serving/generate program (the program holds int8 weights —
    the HBM win) and through `jit.save` artifacts (the .pdiparams.npz
    stores int8 + scales)."""

    def __init__(self, layer: Linear, per_channel=True):
        super().__init__()
        self.in_features = layer.in_features
        self.out_features = layer.out_features
        w_int8, scale = quantize_weight_int8(unwrap(layer.weight),
                                             per_channel=per_channel,
                                             axis=1)
        self.register_buffer("w_int8", Tensor(w_int8, stop_gradient=True))
        self.register_buffer("w_scale", Tensor(scale.reshape(1, -1),
                                               stop_gradient=True))
        self.bias = layer.bias

    def forward(self, x):
        from ..ops.int8_matmul import dequant_matmul
        y = dequant_matmul(unwrap(x), unwrap(self.w_int8),
                           unwrap(self.w_scale))
        if self.bias is not None:
            y = y + unwrap(self.bias)
        return Tensor(y, stop_gradient=True)

    def extra_repr(self):
        return (f"in_features={self.in_features}, "
                f"out_features={self.out_features}, int8 weight-only")


def quantize_for_serving(model: Layer, quantize: str = "int8",
                         per_channel: bool = True) -> Layer:
    """Post-training int8 WEIGHT-ONLY conversion for the serving /
    generate path: swaps every Linear in place for `Int8WeightOnlyLinear`
    (no calibration pass needed — activations are untouched).  Returns
    the same model object.  Embeddings and tied LM heads stay fp
    (quantizing the tied weight would also perturb the embedding lookup);
    the Linears carry the bulk of a transformer's weight bytes, which is
    where the decode HBM traffic lives.  Wired through
    ``inference.Config.enable_serving(..., quantize="int8")``; a
    quantized model runs through the UNCHANGED serving programs (same
    compile bound — the int8 buffers are just different-dtype state
    inputs)."""
    if quantize != "int8":
        raise ValueError(
            f"quantize_for_serving supports 'int8', got {quantize!r}")

    def factory(child):
        if isinstance(child, Linear):
            return Int8WeightOnlyLinear(child, per_channel=per_channel)
        return None
    return _replace_layers(model, factory)


class PostTrainingQuantization:
    """PTQ driver (reference: post_training_quantization.py:1).

    1) observers = ptq.prepare(model)  — installs abs-max input observers
    2) run calibration batches through the model (eval mode)
    3) q_model = ptq.convert(model)    — int8 weights + static act scales
    """

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantize_activations=True):
        self._wbits = weight_bits
        self._abits = activation_bits
        self._quant_act = quantize_activations
        self._observed = {}
        self._hooks = []

    def prepare(self, model: Layer) -> Layer:
        self._observed.clear()

        def make_hook(key):
            def hook(layer, inputs):
                if inputs and isinstance(inputs[0], Tensor):
                    cur = float(jnp.max(jnp.abs(unwrap(inputs[0]))))
                    prev = self._observed.get(key, 0.0)
                    self._observed[key] = max(prev, cur)
                return None
            return hook

        for name, sub in model.named_sublayers():
            if isinstance(sub, (Linear, Conv2D)):
                self._hooks.append(
                    sub.register_forward_pre_hook(make_hook(id(sub))))
        return model

    def convert(self, model: Layer) -> Layer:
        for h in self._hooks:
            h.remove()
        self._hooks = []

        def factory(child):
            act = None
            if self._quant_act and id(child) in self._observed:
                act = jnp.float32(self._observed[id(child)])
            if isinstance(child, Linear):
                return Int8Linear(child, self._wbits, act, self._abits)
            if isinstance(child, Conv2D):
                return Int8Conv2D(child, self._wbits, act, self._abits)
            return None
        return _replace_layers(model, factory)
