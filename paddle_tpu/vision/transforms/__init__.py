"""Vision transforms (reference: python/paddle/vision/transforms/ —
numpy/PIL host-side preprocessing).  All transforms are numpy-based host ops
(they run in DataLoader workers, never on the TPU); ToTensor produces the
CHW float32 array the models consume.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class BaseTransform:
    def __call__(self, x):
        return self._apply_image(np.asarray(x))


def _chw(img):
    """HWC/HW -> HWC ndarray."""
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


class ToTensor(BaseTransform):
    """HWC [0,255] -> CHW float32 [0,1] (reference to_tensor)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = _chw(img)
        # scale by dtype (deterministic), like the reference: uint8 -> /255
        if img.dtype == np.uint8:
            img = img.astype(np.float32) / 255.0
        else:
            img = img.astype(np.float32)
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            n = img.shape[0]
            return (img - self.mean[:n, None, None]) / self.std[:n, None, None]
        n = img.shape[-1]
        return (img - self.mean[:n]) / self.std[:n]


class Resize(BaseTransform):
    """Nearest/bilinear resize without PIL (numpy index math)."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _chw(img)
        h, w = img.shape[:2]
        th, tw = self.size
        if (h, w) == (th, tw):
            return img
        if self.interpolation == "nearest":
            ys = (np.arange(th) * h / th).astype(int).clip(0, h - 1)
            xs = (np.arange(tw) * w / tw).astype(int).clip(0, w - 1)
            return img[ys][:, xs]
        # bilinear
        ys = (np.arange(th) + 0.5) * h / th - 0.5
        xs = (np.arange(tw) + 0.5) * w / tw - 0.5
        y0 = np.floor(ys).astype(int).clip(0, h - 1)
        x0 = np.floor(xs).astype(int).clip(0, w - 1)
        y1 = (y0 + 1).clip(0, h - 1)
        x1 = (x0 + 1).clip(0, w - 1)
        wy = (ys - y0).clip(0, 1)[:, None, None]
        wx = (xs - x0).clip(0, 1)[None, :, None]
        f = img.astype(np.float32)
        out = (f[y0][:, x0] * (1 - wy) * (1 - wx)
               + f[y0][:, x1] * (1 - wy) * wx
               + f[y1][:, x0] * wy * (1 - wx)
               + f[y1][:, x1] * wy * wx)
        return out.astype(img.dtype) if img.dtype == np.uint8 else out


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = _chw(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = _chw(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, ((p, p), (p, p), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(0, h - th))
        j = random.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _chw(img)[:, ::-1].copy()
        return _chw(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return _chw(img)[::-1].copy()
        return _chw(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        img = _chw(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= w and ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                return self._resize(img[i:i + ch, j:j + cw])
        return self._resize(CenterCrop(min(h, w))(img))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_chw(img), self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding,) * 4
        self.padding = padding
        self.fill = fill
        self.mode = padding_mode

    def _apply_image(self, img):
        l, t, r, b = (self.padding if len(self.padding) == 4
                      else tuple(self.padding) * 2)
        img = _chw(img)
        if self.mode == "constant":
            return np.pad(img, ((t, b), (l, r), (0, 0)),
                          constant_values=self.fill)
        return np.pad(img, ((t, b), (l, r), (0, 0)), mode=self.mode)


class RandomRotation(BaseTransform):
    """Arbitrary-angle rotation via inverse-map bilinear sampling (numpy;
    no scipy/PIL needed)."""

    def __init__(self, degrees, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = tuple(degrees)
        self.fill = fill

    def _apply_image(self, img):
        img = _chw(img)
        angle = np.deg2rad(random.uniform(*self.degrees))
        h, w = img.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h, dtype=np.float32),
                             np.arange(w, dtype=np.float32), indexing="ij")
        c, s = np.cos(angle), np.sin(angle)
        # inverse rotation: output pixel samples source location
        sx = c * (xx - cx) + s * (yy - cy) + cx
        sy = -s * (xx - cx) + c * (yy - cy) + cy
        x0 = np.floor(sx).astype(int)
        y0 = np.floor(sy).astype(int)
        wx = (sx - x0)[..., None]
        wy = (sy - y0)[..., None]
        valid = (sx >= 0) & (sx <= w - 1) & (sy >= 0) & (sy <= h - 1)
        x0c = x0.clip(0, w - 1)
        y0c = y0.clip(0, h - 1)
        x1c = (x0 + 1).clip(0, w - 1)
        y1c = (y0 + 1).clip(0, h - 1)
        f = img.astype(np.float32)
        out = (f[y0c, x0c] * (1 - wy) * (1 - wx) + f[y0c, x1c] * (1 - wy) * wx
               + f[y1c, x0c] * wy * (1 - wx) + f[y1c, x1c] * wy * wx)
        out = np.where(valid[..., None], out, np.float32(self.fill))
        return out.astype(img.dtype) if img.dtype == np.uint8 else out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def _apply_image(self, img):
        img = _chw(img).astype(np.float32)
        if img.shape[2] >= 3:
            g = (0.299 * img[..., 0] + 0.587 * img[..., 1]
                 + 0.114 * img[..., 2])
        else:
            g = img[..., 0]
        return np.repeat(g[:, :, None], self.n, axis=2)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(_chw(img).astype(np.float32) * alpha, 0,
                       255 if np.asarray(img).dtype == np.uint8 else 1e30)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        img = _chw(img).astype(np.float32)
        mean = img.mean()
        a = 1 + np.random.uniform(-self.value, self.value)
        return np.clip((img - mean) * a + mean, 0, 255)


class ColorJitter(BaseTransform):
    """brightness/contrast/saturation/hue jitter.  Saturation = blend with
    luma; hue = rotation in the YIQ chroma plane (the classic matrix trick,
    avoiding an HSV round-trip)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        img = _chw(img)
        hi = 255.0 if img.dtype == np.uint8 else max(1.0, float(img.max()))
        out = img.astype(np.float32)
        if self.brightness:
            out = out * (1 + np.random.uniform(-self.brightness,
                                               self.brightness))
        if self.contrast:
            mean = out.mean()
            out = (out - mean) * (1 + np.random.uniform(
                -self.contrast, self.contrast)) + mean
        if self.saturation and out.shape[2] >= 3:
            luma = (0.299 * out[..., 0] + 0.587 * out[..., 1]
                    + 0.114 * out[..., 2])[..., None]
            a = 1 + np.random.uniform(-self.saturation, self.saturation)
            out = np.concatenate(
                [luma + a * (out[..., :3] - luma), out[..., 3:]], axis=2)
        if self.hue and out.shape[2] >= 3:
            theta = np.random.uniform(-self.hue, self.hue) * 2 * np.pi
            c, s = np.cos(theta), np.sin(theta)
            to_yiq = np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.322],
                               [0.211, -0.523, 0.312]], np.float32)
            rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
            m = np.linalg.inv(to_yiq) @ rot @ to_yiq
            out = np.concatenate(
                [out[..., :3] @ m.T, out[..., 3:]], axis=2)
        return np.clip(out, 0, hi)


class SaturationTransform(BaseTransform):
    """Saturation jitter alone (reference transforms.py SaturationTransform:
    factor 1±value) — the ColorJitter luma-blend with one knob."""

    def __init__(self, value, keys=None):
        self.value = value
        self._jitter = ColorJitter(saturation=value)

    def _apply_image(self, img):
        return self._jitter._apply_image(img)


class HueTransform(BaseTransform):
    """Hue jitter alone (reference transforms.py:804 HueTransform, value in
    [0, 0.5]) — the ColorJitter YIQ chroma rotation with one knob."""

    def __init__(self, value, keys=None):
        self.value = value
        self._jitter = ColorJitter(hue=value)

    def _apply_image(self, img):
        return self._jitter._apply_image(img)


# functional aliases (paddle.vision.transforms.functional subset)
def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW"):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return _chw(np.asarray(img))[:, ::-1].copy()


def vflip(img):
    return _chw(np.asarray(img))[::-1].copy()


def center_crop(img, size):
    return CenterCrop(size)(img)


def crop(img, top, left, height, width):
    return _chw(np.asarray(img))[top:top + height, left:left + width]


# reference layout exposes transforms.transforms / transforms.functional
# module names; the implementations live flat in this package
import sys as _sys
transforms = _sys.modules[__name__]
functional = _sys.modules[__name__]
