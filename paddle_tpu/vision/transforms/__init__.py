"""vision transforms (filled out in build-out)."""
