"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models, datasets, transforms, ops  # noqa: F401
