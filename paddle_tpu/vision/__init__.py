"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models, datasets, transforms  # noqa: F401
