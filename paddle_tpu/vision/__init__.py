"""paddle_tpu.vision (reference: python/paddle/vision/)."""
from . import models, datasets, transforms, ops  # noqa: F401
from . import image  # noqa: F401,E402
from .image import set_image_backend, get_image_backend, image_load  # noqa: F401,E402
