"""ResNet family (reference: python/paddle/vision/models/resnet.py —
ResNet18/34/50/101/152 with BasicBlock/BottleneckBlock).

TPU-first: convs lower through nn.functional.conv2d →
lax.conv_general_dilated which XLA lays out for the MXU; BN folds into
conv at inference via XLA fusion.  `data_format="NHWC"` runs the whole
trunk channels-last — the TPU-preferred layout (r4 probe: conv tower
~13% faster than NCHW at ResNet-50 shapes, no relayout transposes);
inputs must then be (N, H, W, C) like paddle's own data_format contract.
"""
from __future__ import annotations

from ... import nn


_ACCEPTS_POOL: dict = {}  # norm type -> forward_fused takes pool=


def _accepts_pool(norm) -> bool:
    """One signature inspection per norm type: PR-1-era custom norms with
    forward_fused(x, activation, residual) must keep their fusion (and a
    TypeError raised INSIDE a fused path must propagate, not silently
    reroute and re-run hooks)."""
    key = type(norm)
    hit = _ACCEPTS_POOL.get(key)
    if hit is None:
        import inspect
        try:
            hit = "pool" in inspect.signature(norm.forward_fused).parameters
        except (TypeError, ValueError):
            hit = False
        _ACCEPTS_POOL[key] = hit
    return hit


def _bn_act(norm, x, activation=None, residual=None, pool=None):
    """Route a block's norm+residual+act(+pool epilogue) tail through the
    fused kernel path (ops/fused_bn_act.py) when the norm layer supports
    it; custom norm_layer callables without forward_fused get the
    composite, ones without the pool epilogue get fused bn/act + a
    separate pool."""
    if hasattr(norm, "forward_fused"):
        if pool is None:
            return norm.forward_fused(x, activation=activation,
                                      residual=residual)
        if _accepts_pool(norm):
            return norm.forward_fused(x, activation=activation,
                                      residual=residual, pool=pool)
        out = norm.forward_fused(x, activation=activation,
                                 residual=residual)
    else:
        from ...nn.functional.norm import bn_act_composite
        out = bn_act_composite(norm(x), activation, residual)
    if pool is not None:
        from ...nn.functional.norm import _pool_composite
        from ...ops.fused_bn_act import _pool_norm
        out = _pool_composite(out, _pool_norm(pool),
                              getattr(norm, "data_format", "NCHW"))
    return out


def _bn_add_act(norm, x, norm_res, res_pre, activation=None):
    """Downsample-shortcut fusion: act(norm(x) + norm_res(res_pre)) as one
    dual-BN op when both norms are stock BatchNorm, else the composite."""
    from ...nn.layer.norm import dual_bn_act, supports_dual_bn
    if supports_dual_bn(norm, norm_res):
        return dual_bn_act(norm, x, norm_res, res_pre,
                           activation=activation)
    return _bn_act(norm, x, activation, residual=norm_res(res_pre))


def _split_downsample(downsample):
    """(conv, stock-BatchNorm) halves of a downsample Sequential when the
    dual-BN fusion applies, else None (custom norm layers / projections)."""
    from ...nn.layer.norm import supports_dual_bn
    if not isinstance(downsample, nn.Sequential) or len(downsample) != 2:
        return None
    conv, norm = downsample[0], downsample[1]
    if not (isinstance(conv, nn.Conv2D) and supports_dual_bn(norm)):
        return None
    return conv, norm


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        # recompute segment boundary (jit.recompute_policy("stages")):
        # block granularity keeps the recompute interior to ONE block —
        # whole-stage segments hold a full stage's activations live while
        # rematerializing and measure WORSE than no recompute
        self._remat_stage = True
        norm_layer = norm_layer or nn.BatchNorm2D
        # only pass the kwarg off-default: custom norm_layer callables
        # need not accept data_format in NCHW mode
        df = {} if data_format == "NCHW" else dict(data_format=data_format)
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, **df)
        self.bn1 = norm_layer(planes, **df)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               **df)
        self.bn2 = norm_layer(planes, **df)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        out = _bn_act(self.bn1, self.conv1(x), "relu")
        out = self.conv2(out)
        ds = _split_downsample(self.downsample)
        if ds is not None:
            # downsample-shortcut add fused with bn2 into one dual-BN op:
            # the normalized shortcut never round-trips HBM on its own
            return _bn_add_act(self.bn2, out, ds[1], ds[0](x), "relu")
        identity = self.downsample(x) if self.downsample is not None else x
        # bn2 + residual-add + relu fused into one kernel (one HBM pass)
        return _bn_act(self.bn2, out, "relu", residual=identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        self._remat_stage = True  # recompute segment (see BasicBlock)
        norm_layer = norm_layer or nn.BatchNorm2D
        # only pass the kwarg off-default: custom norm_layer callables
        # need not accept data_format in NCHW mode
        df = {} if data_format == "NCHW" else dict(data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False, **df)
        self.bn1 = norm_layer(width, **df)
        self.conv2 = nn.Conv2D(width, width, 3, stride=stride, padding=dilation,
                               groups=groups, dilation=dilation,
                               bias_attr=False, **df)
        self.bn2 = norm_layer(width, **df)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, **df)
        self.bn3 = norm_layer(planes * self.expansion, **df)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        out = _bn_act(self.bn1, self.conv1(x), "relu")
        out = _bn_act(self.bn2, self.conv2(out), "relu")
        out = self.conv3(out)
        ds = _split_downsample(self.downsample)
        if ds is not None:
            # downsample-shortcut add fused with bn3 into one dual-BN op
            return _bn_add_act(self.bn3, out, ds[1], ds[0](x), "relu")
        identity = self.downsample(x) if self.downsample is not None else x
        # bn3 + residual-add + relu fused into one kernel (one HBM pass)
        return _bn_act(self.bn3, out, "relu", residual=identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.data_format = data_format
        self._norm_layer = nn.BatchNorm2D
        self.inplanes = 64
        self.dilation = 1
        df = {} if data_format == "NCHW" else dict(data_format=data_format)

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, **df)
        self.bn1 = self._norm_layer(self.inplanes, **df)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, **df)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), **df)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        df = ({} if self.data_format == "NCHW"
              else dict(data_format=self.data_format))
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, **df),
                norm_layer(planes * block.expansion, **df))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, norm_layer=norm_layer,
                        data_format=self.data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer,
                                data_format=self.data_format))
        return nn.Sequential(*layers)

    def forward(self, x, labels=None):
        """Logits, or — with `labels` — per-sample CE losses via the fused
        classifier tail (ops/fused_ce.py: global-avg-pool -> matmul ->
        softmax-CE in one op; the feature map and logits never round-trip
        HBM separately).  The GPT pretraining-head convention: drive a
        TrainStep with batch (x, labels, labels) and a mean loss_fn."""
        if labels is not None and not (self.with_pool
                                       and self.num_classes > 0):
            raise ValueError(
                "ResNet.forward(labels=...): the fused classifier tail "
                "needs with_pool=True and num_classes>0 (this model has "
                f"with_pool={self.with_pool}, "
                f"num_classes={self.num_classes})")
        # stem: conv -> BN -> relu -> maxpool with the pool folded into
        # the fused BN/act epilogue (one op, pooled output only); a
        # replaced/custom maxpool keeps its own forward
        from ...ops.fused_bn_act import fusable_pool_spec
        pool = fusable_pool_spec(self.maxpool, self.data_format)
        if pool is not None:
            x = _bn_act(self.bn1, self.conv1(x), "relu", pool=pool)
        else:
            x = self.maxpool(_bn_act(self.bn1, self.conv1(x), "relu"))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if labels is not None:
            from ...ops.fused_ce import fused_pool_linear_cross_entropy
            return fused_pool_linear_cross_entropy(
                x, self.fc.weight, labels, bias=self.fc.bias,
                data_format=self.data_format)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


def _resnet(depth, block, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no network access); "
            "load a state dict via set_state_dict")
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(18, BasicBlock, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(34, BasicBlock, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(50, BottleneckBlock, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(101, BottleneckBlock, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(152, BottleneckBlock, pretrained, **kwargs)
