"""Vision model zoo (reference: python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, BasicBlock, BottleneckBlock,  # noqa: F401
                     resnet18, resnet34, resnet50, resnet101, resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenet import (MobileNetV1, MobileNetV2,  # noqa: F401
                        mobilenet_v1, mobilenet_v2)
from .ssd import (MultiBoxHead, SSDMobileNetV1,  # noqa: F401
                  ssd_mobilenet_v1)


# reference module-name aliases (models.mobilenetv1/mobilenetv2 modules)
from . import mobilenet as mobilenetv1  # noqa: F401,E402
from . import mobilenet as mobilenetv2  # noqa: F401,E402
