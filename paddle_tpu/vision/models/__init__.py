from . import lenet  # noqa: F401
from .lenet import LeNet  # noqa: F401
