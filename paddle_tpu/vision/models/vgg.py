"""VGG (reference: python/paddle/vision/models/vgg.py — VGG11/13/16/19)."""
from __future__ import annotations

from ... import nn

_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class _Features(nn.Sequential):
    """Sequential that runs a BatchNorm2D immediately followed by ReLU as
    ONE fused bn+relu op — and, when a MaxPool2D follows the ReLU, folds
    the pool into the same op's epilogue (same sublayers and state_dict
    keys as a plain Sequential — only the execution is fused)."""

    def __init__(self, *layers):
        super().__init__(*layers)
        self._remat_stage = True  # jit.recompute_policy("stages") boundary

    def forward(self, x):
        from ...ops.fused_bn_act import fusable_pool_spec
        layers = list(self._sub_layers.values())
        i = 0
        while i < len(layers):
            layer = layers[i]
            nxt = layers[i + 1] if i + 1 < len(layers) else None
            if hasattr(layer, "forward_fused") and isinstance(nxt, nn.ReLU):
                pool = (fusable_pool_spec(
                            layers[i + 2],
                            getattr(layer, "data_format", "NCHW"))
                        if i + 2 < len(layers) else None)
                if pool is not None:
                    x = layer.forward_fused(x, activation="relu", pool=pool)
                    i += 3
                else:
                    x = layer.forward_fused(x, activation="relu")
                    i += 2
            else:
                x = layer(x)
                i += 1
        return x


def _make_layers(cfg, batch_norm=False):
    layers = []
    in_c = 3
    for v in cfg:
        if v == "M":
            layers.append(nn.MaxPool2D(2, 2))
        else:
            layers.append(nn.Conv2D(in_c, v, 3, padding=1))
            if batch_norm:
                layers.append(nn.BatchNorm2D(v))
            layers.append(nn.ReLU())
            in_c = v
    return _Features(*layers)


class VGG(nn.Layer):
    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.num_classes = num_classes
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(),
                nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def _vgg(arch, cfg, batch_norm, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return VGG(_make_layers(_CFGS[cfg], batch_norm), **kwargs)


def vgg11(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg11", "A", batch_norm, pretrained, **kwargs)


def vgg13(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg13", "B", batch_norm, pretrained, **kwargs)


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg16", "D", batch_norm, pretrained, **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return _vgg("vgg19", "E", batch_norm, pretrained, **kwargs)
