"""SSD detection family: MultiBoxHead + SSD over a MobileNetV1 backbone.

Reference: fluid/layers/detection.py:2106 multi_box_head (LayerHelper-built
conv heads + prior_box per feature map, concatenated) — rebuilt as a proper
Layer (this repo's answer to LayerHelper params, like nn/legacy_layers.py),
so the whole model trains through TrainStep and serves through the padded
NMS path.  The classic SSD-MobileNet wiring follows the reference's
PaddleCV/ssd mobilenet_ssd config (extra depthwise blocks + 6 heads).
"""
from __future__ import annotations

import math

from ... import nn
from ...tensor.manipulation import concat, flatten, reshape, transpose
from .. import ops as vops
from .mobilenet import ConvBNLayer, DepthwiseSeparable, MobileNetV1

__all__ = ["MultiBoxHead", "SSDMobileNetV1", "ssd_mobilenet_v1"]


def _num_priors(min_size, max_size, aspect_ratio, flip):
    """Prior count per cell, matching prior_box's wh enumeration."""
    if not isinstance(min_size, (list, tuple)):
        min_size = [min_size]
    if max_size is not None and not isinstance(max_size, (list, tuple)):
        max_size = [max_size]
    ars = [1.0]
    for ar in (aspect_ratio or []):
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    return len(ars) * len(min_size) + (len(max_size) if max_size else 0)


def _ratio_schedule(base_size, min_ratio, max_ratio, num_layer):
    """The reference's min/max size derivation (detection.py:2285-2294):
    first head at base*0.10/0.20, rest on an even ratio walk."""
    min_sizes, max_sizes = [], []
    step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
    for ratio in range(min_ratio, max_ratio + 1, step):
        min_sizes.append(base_size * ratio / 100.0)
        max_sizes.append(base_size * (ratio + step) / 100.0)
    return ([base_size * 0.10] + min_sizes[:num_layer - 1],
            [base_size * 0.20] + max_sizes[:num_layer - 1])


class MultiBoxHead(nn.Layer):
    """SSD prediction head over a list of feature maps.

    forward(feats, image) -> (mbox_locs (N, P, 4), mbox_confs (N, P, C),
    boxes (P, 4), variances (P, 4)) with P the total prior count — the
    reference multi_box_head's four outputs.
    """

    def __init__(self, in_channels, base_size, num_classes, aspect_ratios,
                 min_ratio=None, max_ratio=None, min_sizes=None,
                 max_sizes=None, steps=None, step_w=None, step_h=None,
                 offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                 clip=False, kernel_size=1, pad=0, stride=1,
                 min_max_aspect_ratios_order=False):
        super().__init__()
        num_layer = len(in_channels)
        if min_sizes is None:
            if num_layer < 3 or min_ratio is None or max_ratio is None:
                raise ValueError(
                    "multi_box_head: give min_sizes/max_sizes explicitly, "
                    "or min_ratio/max_ratio with >= 3 feature maps")
            min_sizes, max_sizes = _ratio_schedule(
                base_size, min_ratio, max_ratio, num_layer)
        self.min_sizes = min_sizes
        self.max_sizes = max_sizes
        self.aspect_ratios = aspect_ratios
        self.num_classes = num_classes
        self.variance = tuple(variance)
        self.flip = flip
        self.clip = clip
        self.offset = offset
        self.min_max_aspect_ratios_order = min_max_aspect_ratios_order
        if steps is not None:
            step_w = step_h = None
            self.steps = steps
        else:
            self.steps = None
        self.step_w = step_w
        self.step_h = step_h

        locs, confs = [], []
        for i, cin in enumerate(in_channels):
            ms = min_sizes[i]
            mx = max_sizes[i] if max_sizes else None
            ar = aspect_ratios[i] if aspect_ratios else []
            if not isinstance(ar, (list, tuple)):
                ar = [ar]
            np_i = _num_priors(ms, mx, ar, flip)
            locs.append(nn.Conv2D(cin, np_i * 4, kernel_size,
                                  stride=stride, padding=pad))
            confs.append(nn.Conv2D(cin, np_i * num_classes, kernel_size,
                                   stride=stride, padding=pad))
        self.loc_convs = nn.LayerList(locs)
        self.conf_convs = nn.LayerList(confs)

    def _level_steps(self, i):
        if self.steps is not None:
            s = self.steps[i]
            return (s, s) if not isinstance(s, (list, tuple)) else tuple(s)
        if self.step_w is not None:
            return (self.step_w[i], self.step_h[i])
        return (0.0, 0.0)

    def forward(self, feats, image):
        locs, confs, boxes, vars_ = [], [], [], []
        for i, feat in enumerate(feats):
            ms = self.min_sizes[i]
            mx = self.max_sizes[i] if self.max_sizes else None
            ar = self.aspect_ratios[i] if self.aspect_ratios else []
            if not isinstance(ar, (list, tuple)):
                ar = [ar]
            ms_l = ms if isinstance(ms, (list, tuple)) else [ms]
            mx_l = (mx if isinstance(mx, (list, tuple)) else [mx]) \
                if mx is not None else None
            box, var = vops.prior_box(
                feat, image, ms_l, mx_l, ar, self.variance, flip=self.flip,
                clip=self.clip, steps=self._level_steps(i),
                offset=self.offset,
                min_max_aspect_ratios_order=self.min_max_aspect_ratios_order)
            boxes.append(reshape(box, [-1, 4]))
            vars_.append(reshape(var, [-1, 4]))
            n = feat.shape[0]
            loc = transpose(self.loc_convs[i](feat), [0, 2, 3, 1])
            locs.append(reshape(flatten(loc, 1), [n, -1, 4]))
            conf = transpose(self.conf_convs[i](feat), [0, 2, 3, 1])
            confs.append(reshape(flatten(conf, 1),
                                 [n, -1, self.num_classes]))
        return (concat(locs, axis=1), concat(confs, axis=1),
                concat(boxes, axis=0), concat(vars_, axis=0))


class _MobileNetV1Feats(nn.Layer):
    """MobileNetV1 trunk exposing the two SSD tap points (conv4_3-analogue
    after block 10 and the final block), headless."""

    def __init__(self, scale=1.0):
        super().__init__()
        base = MobileNetV1(scale=scale, num_classes=0, with_pool=False)
        self.conv1 = base.conv1
        self.blocks = base.blocks

    def forward(self, x):
        x = self.conv1(x)
        feats = []
        for i, blk in enumerate(self.blocks):
            x = blk(x)
            if i == 10:      # 512-ch stride-16 map
                feats.append(x)
        feats.append(x)      # 1024-ch stride-32 map
        return feats


class SSDMobileNetV1(nn.Layer):
    """SSD-MobileNetV1 (the reference PaddleCV mobilenet_ssd lineage):
    MobileNetV1 trunk + depthwise extra blocks + MultiBoxHead.

    forward(image) -> (locs (N, P, 4), confs (N, P, C), boxes, vars);
    `postprocess` runs the padded on-device NMS serving path.
    """

    def __init__(self, num_classes=21, scale=1.0, img_size=300):
        super().__init__()
        self.num_classes = num_classes
        self.backbone = _MobileNetV1Feats(scale)
        c = lambda ch: max(8, int(ch * scale))
        self.extra1 = nn.Sequential(ConvBNLayer(c(1024), c(256), 1),
                                    ConvBNLayer(c(256), c(512), 3, stride=2,
                                                padding=1))
        self.extra2 = nn.Sequential(ConvBNLayer(c(512), c(128), 1),
                                    ConvBNLayer(c(128), c(256), 3, stride=2,
                                                padding=1))
        self.extra3 = nn.Sequential(ConvBNLayer(c(256), c(128), 1),
                                    ConvBNLayer(c(128), c(256), 3, stride=2,
                                                padding=1))
        self.extra4 = nn.Sequential(ConvBNLayer(c(256), c(64), 1),
                                    ConvBNLayer(c(64), c(128), 3, stride=2,
                                                padding=1))
        self.head = MultiBoxHead(
            in_channels=[c(512), c(1024), c(512), c(256), c(256), c(128)],
            base_size=img_size, num_classes=num_classes,
            aspect_ratios=[[2.0], [2.0, 3.0], [2.0, 3.0], [2.0, 3.0],
                           [2.0, 3.0], [2.0, 3.0]],
            min_ratio=20, max_ratio=90, flip=True)

    def forward(self, image):
        feats = list(self.backbone(image))
        x = feats[-1]
        for extra in (self.extra1, self.extra2, self.extra3, self.extra4):
            x = extra(x)
            feats.append(x)
        return self.head(feats, image)

    def postprocess(self, locs, confs, boxes, vars_, score_threshold=0.01,
                    nms_threshold=0.45, keep_top_k=200, nms_top_k=400):
        """Serve: detection_output (softmax + decode + padded multiclass
        NMS, fully on device — the softmax lives inside detection_output,
        matching the reference contract, detection.py:721)."""
        return vops.detection_output(
            locs, confs, boxes, vars_,
            background_label=0, nms_threshold=nms_threshold,
            nms_top_k=nms_top_k, keep_top_k=keep_top_k,
            score_threshold=score_threshold)


def ssd_mobilenet_v1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights are not bundled (no network access); "
            "load a state dict via set_state_dict")
    return SSDMobileNetV1(**kwargs)
