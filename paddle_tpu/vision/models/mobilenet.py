"""MobileNetV1/V2 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py — depthwise-separable convs; depthwise = grouped conv which
lax.conv_general_dilated expresses via feature_group_count)."""
from __future__ import annotations

from ... import nn


class ConvBNLayer(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0, groups=1,
                 act="relu"):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self._act_name = act if act in ("relu", "relu6") else None

    def forward(self, x):
        # BN + act fused (ops/fused_bn_act.py) — the conv-bn-act idiom
        return self.bn.forward_fused(self.conv(x),
                                     activation=self._act_name)

    def forward_residual(self, x, residual):
        """conv -> BN + residual-add (+act) as one fused op — the
        inverted-residual tail (the add shares the BN's elementwise
        tile instead of costing its own HBM pass)."""
        return self.bn.forward_fused(self.conv(x),
                                     activation=self._act_name,
                                     residual=residual)


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, stride, scale):
        super().__init__()
        c1 = int(out_c1 * scale)
        c2 = int(out_c2 * scale)
        self.dw = ConvBNLayer(int(in_c * scale), c1, 3, stride=stride,
                              padding=1, groups=int(in_c * scale))
        self.pw = ConvBNLayer(c1, c2, 1)
        self._remat_stage = True  # jit.recompute_policy("stages") boundary

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = ConvBNLayer(3, int(32 * scale), 3, stride=2, padding=1)
        cfg = [(32, 32, 64, 1), (64, 64, 128, 2), (128, 128, 128, 1),
               (128, 128, 256, 2), (256, 256, 256, 1), (256, 256, 512, 2),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
               (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 1024, 2),
               (1024, 1024, 1024, 1)]
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, o1, o2, s, scale) for i, o1, o2, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x, labels=None):
        if labels is not None and not (self.with_pool
                                       and self.num_classes > 0):
            raise ValueError(
                "MobileNetV1.forward(labels=...): the fused classifier "
                "tail needs with_pool=True and num_classes>0 (this model "
                f"has with_pool={self.with_pool}, "
                f"num_classes={self.num_classes})")
        x = self.blocks(self.conv1(x))
        if labels is not None:
            # fused classifier tail: pool + matmul + softmax-CE as one op
            from ...ops.fused_ce import fused_pool_linear_cross_entropy
            return fused_pool_linear_cross_entropy(
                x, self.fc.weight, labels, bias=self.fc.bias)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.fc(flatten(x, 1))
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNLayer(inp, hidden, 1, act="relu6"))
        layers += [
            ConvBNLayer(hidden, hidden, 3, stride=stride, padding=1,
                        groups=hidden, act="relu6"),
            ConvBNLayer(hidden, oup, 1, act=None),
        ]
        self.conv = nn.Sequential(*layers)
        self._remat_stage = True  # jit.recompute_policy("stages") boundary

    def forward(self, x):
        if not self.use_res:
            return self.conv(x)
        proj = self.conv[len(self.conv) - 1]
        # residual add fused into the projection BN (one elementwise
        # pass) — only for the stock layer with no hooks: the fused call
        # bypasses the projection ConvBNLayer's __call__ AND the
        # containing Sequential's, so hooks on either keep the composite
        if (type(proj) is not ConvBNLayer or proj._forward_pre_hooks
                or proj._forward_post_hooks
                or self.conv._forward_pre_hooks
                or self.conv._forward_post_hooks):
            return x + self.conv(x)
        out = x
        for layer in list(self.conv)[:-1]:
            out = layer(out)
        return proj.forward_residual(out, x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = int(32 * scale)
        features = [ConvBNLayer(3, in_c, 3, stride=2, padding=1, act="relu6")]
        for t, c, n, s in cfg:
            out_c = int(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        self.last_c = int(1280 * max(1.0, scale))
        features.append(ConvBNLayer(in_c, self.last_c, 1, act="relu6"))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten
            x = self.classifier(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights not bundled")
    return MobileNetV2(scale=scale, **kwargs)
