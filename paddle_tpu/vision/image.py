"""Image IO helpers (reference: python/paddle/vision/image.py —
set_image_backend/get_image_backend/image_load).  Zero-egress image:
backends 'cv2'/'pil' exist only if those packages are importable; numpy
.npy files always load."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_backend = "pil"


def set_image_backend(backend):
    global _backend
    if backend not in ("pil", "cv2"):
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"image backend must be 'pil' or 'cv2', got {backend!r}")
    _backend = backend


def get_image_backend():
    return _backend


def image_load(path, backend=None):
    backend = backend or _backend
    if path.endswith(".npy"):
        return np.load(path)
    if backend == "cv2":
        try:
            import cv2
        except ImportError as e:
            raise ImportError("cv2 backend requested but OpenCV is not "
                              "installed") from e
        return cv2.imread(path)
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "PIL backend requested but Pillow is not installed; .npy "
            "arrays load without any image library") from e
    return Image.open(path)
