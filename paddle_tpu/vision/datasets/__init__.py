"""vision datasets (filled out in build-out)."""
