"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, DatasetFolder).

Zero-egress environment: no downloads.  Each dataset reads the standard
on-disk format when paths are given (idx-ubyte for MNIST, pickled batches
for CIFAR, image folders), and raises a clear error otherwise.  For tests
and benchmarks, `FakeData` generates deterministic synthetic samples.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image dataset (tests/benchmarks)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + int(idx))
        img = rng.randint(0, 256, self.image_shape).astype(np.uint8)
        label = np.array(rng.randint(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
        return img, label


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad MNIST image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad MNIST label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py — idx-ubyte reader."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError(
                f"{type(self).__name__}: downloads unavailable (no network); "
                "pass image_path/label_path to local idx-ubyte files")
        if image_path is None or label_path is None:
            raise ValueError(
                f"{type(self).__name__} requires image_path and label_path "
                "(downloads unavailable in this environment)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        self.transform = transform
        self.mode = mode

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array(int(self.labels[idx]), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, label


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py — pickled-batch tar reader."""

    _fine = False

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError("downloads unavailable (no network); pass "
                               "data_file pointing at the cifar tar.gz")
        if data_file is None:
            raise ValueError("Cifar requires data_file (no downloads)")
        self.transform = transform
        self.mode = mode
        want = (("data_batch" if mode == "train" else "test_batch")
                if not self._fine else
                ("train" if mode == "train" else "test"))
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base.startswith(want):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(np.asarray(d[b"data"]))
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        label = np.array(int(self.labels[idx]), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, label


class Cifar100(Cifar10):
    _fine = True


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """reference: vision/datasets/folder.py — class-per-subdir layout.
    Loads .npy arrays natively; image formats need an installed PIL (gated)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file else
                      fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                "loading image files requires PIL; store .npy arrays or "
                "pass a custom loader") from e
        return np.asarray(Image.open(path).convert("RGB"))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array(label, np.int64)


ImageFolder = DatasetFolder
