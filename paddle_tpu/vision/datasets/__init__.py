"""Vision datasets (reference: python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, DatasetFolder).

Zero-egress environment: no downloads.  Each dataset reads the standard
on-disk format when paths are given (idx-ubyte for MNIST, pickled batches
for CIFAR, image folders), and raises a clear error otherwise.  For tests
and benchmarks, `FakeData` generates deterministic synthetic samples.
"""
from __future__ import annotations

import gzip
import io
import os
import pickle
import struct
import tarfile

import numpy as np

from ...io.dataset import Dataset


class FakeData(Dataset):
    """Deterministic synthetic image dataset (tests/benchmarks)."""

    def __init__(self, num_samples=256, image_shape=(3, 32, 32),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self._seed = seed

    def __len__(self):
        return self.num_samples

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + int(idx))
        img = rng.randint(0, 256, self.image_shape).astype(np.uint8)
        label = np.array(rng.randint(0, self.num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32) / 255.0
        return img, label


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        assert magic == 2051, f"bad MNIST image magic {magic}"
        return np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        assert magic == 2049, f"bad MNIST label magic {magic}"
        return np.frombuffer(f.read(), np.uint8)


class MNIST(Dataset):
    """reference: vision/datasets/mnist.py — idx-ubyte reader."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError(
                f"{type(self).__name__}: downloads unavailable (no network); "
                "pass image_path/label_path to local idx-ubyte files")
        if image_path is None or label_path is None:
            raise ValueError(
                f"{type(self).__name__} requires image_path and label_path "
                "(downloads unavailable in this environment)")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        self.transform = transform
        self.mode = mode

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = np.array(int(self.labels[idx]), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = (img.astype(np.float32) / 255.0)[None]
        return img, label


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference: vision/datasets/cifar.py — pickled-batch tar reader."""

    _fine = False

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download:
            raise RuntimeError("downloads unavailable (no network); pass "
                               "data_file pointing at the cifar tar.gz")
        if data_file is None:
            raise ValueError("Cifar requires data_file (no downloads)")
        self.transform = transform
        self.mode = mode
        want = (("data_batch" if mode == "train" else "test_batch")
                if not self._fine else
                ("train" if mode == "train" else "test"))
        images, labels = [], []
        with tarfile.open(data_file, "r:*") as tf:
            for m in tf.getmembers():
                base = os.path.basename(m.name)
                if base.startswith(want):
                    d = pickle.load(tf.extractfile(m), encoding="bytes")
                    images.append(np.asarray(d[b"data"]))
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        self.images = np.concatenate(images).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx].transpose(1, 2, 0)  # HWC for transforms
        label = np.array(int(self.labels[idx]), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.transpose(2, 0, 1).astype(np.float32) / 255.0
        return img, label


class Cifar100(Cifar10):
    _fine = True


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """reference: vision/datasets/folder.py — class-per-subdir layout.
    Loads .npy arrays natively; image formats need an installed PIL (gated)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file else
                      fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                "loading image files requires PIL; store .npy arrays or "
                "pass a custom loader") from e
        return np.asarray(Image.open(path).convert("RGB"))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array(label, np.int64)


ImageFolder = DatasetFolder


def _decode_image(raw: bytes, to_rgb=True):
    import io as _io
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decoding image archives requires PIL") from e
    img = Image.open(_io.BytesIO(raw))
    return np.asarray(img.convert("RGB") if to_rgb else img)


class _ForkSafeTar:
    """Tar handle reopened per process: DataLoader forks workers, and a
    file descriptor inherited across fork shares its offset — concurrent
    extractfile reads would interleave seeks and corrupt the bytes."""

    def __init__(self, path):
        self._path = path
        self._pid = os.getpid()
        self._tf = tarfile.open(path)
        self.members = {m.name: m for m in self._tf.getmembers()}

    def read(self, name) -> bytes:
        if os.getpid() != self._pid:
            self._tf = tarfile.open(self._path)
            self._pid = os.getpid()
        return self._tf.extractfile(self.members[name]).read()


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation (reference: vision/datasets/voc2012.py —
    VOCtrainval tar; items are (image HWC uint8, label HW uint8))."""

    _SET = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _IMG = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LBL = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    _FLAG = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None):
        if data_file is None:
            raise ValueError("VOC2012 requires data_file (no downloads)")
        if mode.lower() not in self._FLAG:
            raise ValueError(mode)
        self.transform = transform
        self._tar = _ForkSafeTar(data_file)
        names = io.BytesIO(self._tar.read(
            self._SET.format(self._FLAG[mode.lower()])))
        self.keys = [ln.decode("utf-8").strip() for ln in names
                     if ln.strip()]

    def __len__(self):
        return len(self.keys)

    def __getitem__(self, idx):
        key = self.keys[idx]
        img = _decode_image(self._tar.read(self._IMG.format(key)))
        # palette PNG: keep the raw class indices, not RGB
        lbl = _decode_image(self._tar.read(self._LBL.format(key)),
                            to_rgb=False)
        if self.transform is not None:
            img = self.transform(img)
        return img, lbl.astype(np.uint8)


class Flowers(Dataset):
    """Oxford 102 Flowers (reference: vision/datasets/flowers.py —
    102flowers tgz + imagelabels.mat + setid.mat; items are
    (image, label))."""

    _FLAG = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None):
        for arg, nm in ((data_file, "data_file"), (label_file, "label_file"),
                        (setid_file, "setid_file")):
            if arg is None:
                raise ValueError(f"Flowers requires {nm} (no downloads)")
        if mode.lower() not in self._FLAG:
            raise ValueError(mode)
        import scipy.io as scio
        self.transform = transform
        self._tar = _ForkSafeTar(data_file)
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[self._FLAG[mode.lower()]][0]

    def __len__(self):
        return len(self.indexes)

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        img = _decode_image(self._tar.read("jpg/image_%05d.jpg" % index))
        if self.transform is not None:
            img = self.transform(img)
        return img, np.array([self.labels[index - 1]], np.int64)
