"""R-CNN / RetinaNet / EAST detection stragglers.

Reference: python/paddle/fluid/layers/detection.py — rpn_target_assign
(:311), retinanet_target_assign (:70), generate_proposal_labels (:2594),
generate_mask_labels (:2746), retinanet_detection_output (:3104),
locality_aware_nms (:3414), box_decoder_and_assign (:3795),
roi_perspective_transform (:2502), polygon_box_transform
(detection/polygon_box_transform_op.cc:15) over the
rpn_target_assign/generate_proposal_labels/mask_util kernels.

TPU-native split, following the repo's assigner convention
(vision/ops.py bipartite_match): TRAINING-DATA PREP (sampling, matching,
mask rasterization) runs host-side in numpy — it is per-epoch data work
the reference also runs on CPU — while everything that must carry
gradients (gathers of predictions, bilinear warps of features) runs as
dispatched device ops so the tape reaches the network outputs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "rpn_target_assign", "retinanet_target_assign",
    "generate_proposal_labels", "generate_mask_labels",
    "retinanet_detection_output", "locality_aware_nms",
    "box_decoder_and_assign", "roi_perspective_transform",
    "polygon_box_transform",
]


def _np(x):
    return np.asarray(jax.device_get(unwrap(x)))


# module-persistent sampler: a FRESH RandomState per call would resample
# the identical fg/bg subset for the same proposals every step, defeating
# use_random (the reference op draws fresh randomness each step).  It
# reseeds with paddle.seed() (core.rng listener) so seeded runs stay
# reproducible; pass seed= for exact reproducibility of a single call.
_SAMPLER = np.random.RandomState(0)


def _reseed_sampler(s):
    _SAMPLER.seed(s)


from ..core import rng as _core_rng  # noqa: E402
_core_rng.register_seed_listener(_reseed_sampler)


def _rng_for(seed):
    return np.random.RandomState(seed) if seed is not None else _SAMPLER


def _iou_np(a, b):
    """(A, 4) x (B, 4) -> (A, B) IoU, numpy."""
    area_a = np.clip(a[:, 2] - a[:, 0], 0, None) * \
        np.clip(a[:, 3] - a[:, 1], 0, None)
    area_b = np.clip(b[:, 2] - b[:, 0], 0, None) * \
        np.clip(b[:, 3] - b[:, 1], 0, None)
    x1 = np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
    return inter / np.maximum(area_a[:, None] + area_b[None, :] - inter,
                              1e-10)


def _encode_np(anchors, gts, weights=(1.0, 1.0, 1.0, 1.0)):
    """Center-size delta encode, numpy (box_coder encode semantics)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw = gts[:, 2] - gts[:, 0]
    gh = gts[:, 3] - gts[:, 1]
    gcx = gts[:, 0] + gw / 2
    gcy = gts[:, 1] + gh / 2
    eps = 1e-10
    d = np.stack([(gcx - acx) / np.maximum(aw, eps) / weights[0],
                  (gcy - acy) / np.maximum(ah, eps) / weights[1],
                  np.log(np.maximum(gw, eps) / np.maximum(aw, eps))
                  / weights[2],
                  np.log(np.maximum(gh, eps) / np.maximum(ah, eps))
                  / weights[3]], axis=1)
    return d.astype(np.float32)


def _match_anchors(iou, positive_overlap, negative_overlap):
    """Anchor labels: 1 fg (iou>=pos or per-gt argmax), 0 bg (max<neg),
    -1 ignore.  Returns (labels, matched_gt_idx, max_iou)."""
    n_anchor = iou.shape[0]
    labels = np.full((n_anchor,), -1, np.int32)
    if iou.shape[1] == 0:
        return labels, np.zeros((n_anchor,), np.int64), \
            np.zeros((n_anchor,), np.float32)
    max_iou = iou.max(axis=1)
    argmax_gt = iou.argmax(axis=1)
    labels[max_iou < negative_overlap] = 0
    labels[max_iou >= positive_overlap] = 1
    # per-gt best anchor is always positive (ties included)
    best_per_gt = iou.max(axis=0)
    for g in range(iou.shape[1]):
        if best_per_gt[g] > 0:
            labels[iou[:, g] >= best_per_gt[g] - 1e-9] = 1
    return labels, argmax_gt, max_iou.astype(np.float32)


def _gather_rows(pred, flat_idx, n_per_im):
    """Device gather of (N, A, K) predictions at flat (im*A + a) indices —
    dispatched so grads flow back into the network outputs."""
    idx = jnp.asarray(flat_idx, jnp.int32)

    def raw(p):
        return p.reshape((-1,) + p.shape[2:])[idx]

    return dispatch("target_assign_gather", raw, pred)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_count=None, seed=None):
    """Faster-RCNN RPN sampler (reference detection.py:311 over
    rpn_target_assign_op).  bbox_pred (N, A, 4), cls_logits (N, A, 1),
    anchors (A, 4); gt_boxes (N, G, 4) padded dense + gt_count, or a
    per-image list (LoD analogue).  Returns (pred_scores, pred_loc,
    target_label, target_bbox, bbox_inside_weight) with the prediction
    gathers on-device (grads flow); targets are stop-gradient."""
    anchors = _np(anchor_box).reshape(-1, 4)
    av = _np(anchor_var).reshape(-1, 4) if anchor_var is not None else None
    gts, counts = _pad_boxes(gt_boxes, gt_count)
    n = gts.shape[0]
    im_infos = _np(im_info) if im_info is not None else None
    crowd = _np(is_crowd) if is_crowd is not None else None
    rng = _rng_for(seed)

    idx_all, lab_all, tgt_all = [], [], []
    for i in range(n):
        a_mask = np.ones(len(anchors), bool)
        if rpn_straddle_thresh >= 0 and im_infos is not None:
            h, w = float(im_infos[i][0]), float(im_infos[i][1])
            t = rpn_straddle_thresh
            a_mask = ((anchors[:, 0] >= -t) & (anchors[:, 1] >= -t)
                      & (anchors[:, 2] < w + t) & (anchors[:, 3] < h + t))
        gt_i = gts[i, :counts[i]]
        if crowd is not None:
            keep = crowd[i, :counts[i]].reshape(-1) == 0
            gt_i = gt_i[keep]
        iou = _iou_np(anchors[a_mask], gt_i)
        labels, argmax_gt, _ = _match_anchors(
            iou, rpn_positive_overlap, rpn_negative_overlap)
        fg_idx = np.nonzero(labels == 1)[0]
        bg_idx = np.nonzero(labels == 0)[0]
        n_fg = int(rpn_batch_size_per_im * rpn_fg_fraction)
        if len(fg_idx) > n_fg:
            fg_idx = (rng.permutation(fg_idx)[:n_fg] if use_random
                      else fg_idx[:n_fg])
        n_bg = rpn_batch_size_per_im - len(fg_idx)
        if len(bg_idx) > n_bg:
            bg_idx = (rng.permutation(bg_idx)[:n_bg] if use_random
                      else bg_idx[:n_bg])
        inside = np.nonzero(a_mask)[0]
        fg_a = inside[fg_idx]
        bg_a = inside[bg_idx]
        sel = np.concatenate([fg_a, bg_a])
        lab = np.concatenate([np.ones(len(fg_a), np.int32),
                              np.zeros(len(bg_a), np.int32)])
        tgt = _encode_np(anchors[fg_a], gt_i[argmax_gt[fg_idx]])
        if av is not None and len(fg_a):
            tgt = tgt / av[fg_a]
        idx_all.append(sel + i * len(anchors))
        lab_all.append(lab)
        tgt_all.append(tgt)

    flat = np.concatenate(idx_all) if idx_all else np.zeros(0, np.int64)
    labels = np.concatenate(lab_all).astype(np.int32)
    n_fg_total = int((labels == 1).sum())
    fg_flat = np.concatenate(
        [ix[:int((lb == 1).sum())] for ix, lb in zip(idx_all, lab_all)]) \
        if idx_all else np.zeros(0, np.int64)
    score_pred = _gather_rows(cls_logits, flat, None)
    loc_pred = _gather_rows(bbox_pred, fg_flat, None)
    target_bbox = np.concatenate(tgt_all) if tgt_all else \
        np.zeros((0, 4), np.float32)
    return (score_pred, loc_pred,
            Tensor(jnp.asarray(labels.reshape(-1, 1)), stop_gradient=True),
            Tensor(jnp.asarray(target_bbox), stop_gradient=True),
            Tensor(jnp.ones((max(n_fg_total, 0), 4), jnp.float32),
                   stop_gradient=True))


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4,
                            gt_count=None):
    """RetinaNet assigner (reference detection.py:70): every fg/bg anchor
    is kept (focal loss replaces sampling).  Returns (pred_scores,
    pred_loc, target_label, target_bbox, bbox_inside_weight, fg_num)."""
    anchors = _np(anchor_box).reshape(-1, 4)
    av = _np(anchor_var).reshape(-1, 4) if anchor_var is not None else None
    gts, counts = _pad_boxes(gt_boxes, gt_count)
    glab = _np(gt_labels)
    if glab.ndim == 3:
        glab = glab[..., 0]
    crowd = _np(is_crowd) if is_crowd is not None else None
    n = gts.shape[0]
    idx_all, lab_all, tgt_all, fg_counts = [], [], [], []
    for i in range(n):
        gt_i = gts[i, :counts[i]]
        glab_i = glab[i][:counts[i]]
        if crowd is not None:
            keep = crowd[i, :counts[i]].reshape(-1) == 0
            gt_i = gt_i[keep]
            glab_i = glab_i[keep]
        iou = _iou_np(anchors, gt_i)
        labels, argmax_gt, _ = _match_anchors(
            iou, positive_overlap, negative_overlap)
        fg_a = np.nonzero(labels == 1)[0]
        bg_a = np.nonzero(labels == 0)[0]
        sel = np.concatenate([fg_a, bg_a])
        lab = np.concatenate([glab_i[argmax_gt[fg_a]].astype(np.int32),
                              np.zeros(len(bg_a), np.int32)])
        tgt = _encode_np(anchors[fg_a], gt_i[argmax_gt[fg_a]])
        if av is not None and len(fg_a):
            tgt = tgt / av[fg_a]
        idx_all.append(sel + i * len(anchors))
        lab_all.append(lab)
        tgt_all.append(tgt)
        fg_counts.append(len(fg_a))
    flat = np.concatenate(idx_all)
    fg_flat = np.concatenate(
        [ix[:c] for ix, c in zip(idx_all, fg_counts)])
    score_pred = _gather_rows(cls_logits, flat, None)
    loc_pred = _gather_rows(bbox_pred, fg_flat, None)
    labels = np.concatenate(lab_all).astype(np.int32)
    target_bbox = np.concatenate(tgt_all)
    fg_num = np.asarray([[max(sum(fg_counts), 1)]], np.int32)
    return (score_pred, loc_pred,
            Tensor(jnp.asarray(labels.reshape(-1, 1)), stop_gradient=True),
            Tensor(jnp.asarray(target_bbox.astype(np.float32)),
                   stop_gradient=True),
            Tensor(jnp.ones((int(sum(fg_counts)), 4), jnp.float32),
                   stop_gradient=True),
            Tensor(jnp.asarray(fg_num), stop_gradient=True))


def _pad_boxes(gt_boxes, gt_count):
    if isinstance(gt_boxes, (list, tuple)):
        boxes = [_np(b).reshape(-1, 4) for b in gt_boxes]
        m = max(1, max(len(b) for b in boxes))
        out = np.zeros((len(boxes), m, 4), np.float32)
        cnt = np.zeros(len(boxes), np.int64)
        for i, b in enumerate(boxes):
            out[i, :len(b)] = b
            cnt[i] = len(b)
        return out, cnt
    gv = _np(gt_boxes).astype(np.float32)
    if gv.ndim == 2:
        gv = gv[None]
    cnt = (_np(gt_count).astype(np.int64).reshape(-1)
           if gt_count is not None
           else np.full(gv.shape[0], gv.shape[1], np.int64))
    return gv, cnt


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info=None, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             rois_num=None, gt_count=None, seed=None,
                             **_ignored):
    """Faster-RCNN second-stage sampler (reference detection.py:2594 over
    generate_proposal_labels_op): sample fg/bg rois against gt, emit
    class labels + per-class encoded bbox targets with inside/outside
    weights.  Host-side data prep; all outputs stop-gradient.

    rpn_rois: (R, 4) with rois_num (N,), or a per-image list.
    Returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights, rois_num_out)."""
    cls_n = int(class_nums or 81)
    gts, counts = _pad_boxes(gt_boxes, gt_count)
    gcls = _np(gt_classes)
    if gcls.ndim == 3:
        gcls = gcls[..., 0]
    if isinstance(rpn_rois, (list, tuple)):
        roi_list = [_np(r).reshape(-1, 4) for r in rpn_rois]
    else:
        rv = _np(rpn_rois).reshape(-1, 4)
        if rois_num is not None:
            rn = _np(rois_num).astype(np.int64).reshape(-1)
            ofs = np.concatenate([[0], np.cumsum(rn)])
            roi_list = [rv[ofs[i]:ofs[i + 1]] for i in range(len(rn))]
        else:
            roi_list = [rv]
    rng = _rng_for(seed)

    out_rois, out_lab, out_tgt, out_in, out_out, out_n = \
        [], [], [], [], [], []
    for i, rois in enumerate(roi_list):
        gt_i = gts[i, :counts[i]]
        crowd = (_np(is_crowd)[i, :counts[i]].reshape(-1)
                 if is_crowd is not None else np.zeros(counts[i]))
        gt_ok = gt_i[crowd == 0]
        cls_ok = gcls[i][:counts[i]][crowd == 0]
        if not is_cascade_rcnn:
            rois = np.concatenate([rois, gt_ok]) if len(gt_ok) else rois
        iou = _iou_np(rois, gt_ok) if len(gt_ok) else \
            np.zeros((len(rois), 0))
        max_iou = iou.max(axis=1) if iou.shape[1] else \
            np.zeros(len(rois))
        arg_gt = iou.argmax(axis=1) if iou.shape[1] else \
            np.zeros(len(rois), np.int64)
        fg = np.nonzero(max_iou >= fg_thresh)[0]
        bg = np.nonzero((max_iou < bg_thresh_hi)
                        & (max_iou >= bg_thresh_lo))[0]
        n_fg = min(int(batch_size_per_im * fg_fraction), len(fg))
        if len(fg) > n_fg:
            fg = rng.permutation(fg)[:n_fg] if use_random else fg[:n_fg]
        n_bg = min(batch_size_per_im - len(fg), len(bg))
        if len(bg) > n_bg:
            bg = rng.permutation(bg)[:n_bg] if use_random else bg[:n_bg]
        sel = np.concatenate([fg, bg]).astype(np.int64)
        labels = np.zeros(len(sel), np.int32)
        labels[:len(fg)] = cls_ok[arg_gt[fg]].astype(np.int32) \
            if len(fg) else labels[:0]
        tgt = np.zeros((len(sel), 4 * (1 if is_cls_agnostic else cls_n)),
                       np.float32)
        w_in = np.zeros_like(tgt)
        if len(fg):
            enc = _encode_np(rois[fg], gt_ok[arg_gt[fg]],
                             weights=bbox_reg_weights)
            for j, lab in enumerate(labels[:len(fg)]):
                c = 1 if is_cls_agnostic else int(lab)
                tgt[j, 4 * c:4 * c + 4] = enc[j]
                w_in[j, 4 * c:4 * c + 4] = 1.0
        out_rois.append(rois[sel])
        out_lab.append(labels)
        out_tgt.append(tgt)
        out_in.append(w_in)
        out_out.append((w_in > 0).astype(np.float32))
        out_n.append(len(sel))

    def T(x, dtype=np.float32):  # noqa: N802
        return Tensor(jnp.asarray(np.concatenate(x).astype(dtype)),
                      stop_gradient=True)

    return (T(out_rois), T(out_lab, np.int32), T(out_tgt), T(out_in),
            T(out_out),
            Tensor(jnp.asarray(np.asarray(out_n, np.int32)),
                   stop_gradient=True))


def _rasterize_polygons(polys, x0, y0, x1, y1, resolution):
    """Even-odd rasterization of polygons onto a resolution^2 grid over
    the box [x0, x1] x [y0, y1] (the mask_util.cc polys_to_mask role)."""
    m = np.zeros((resolution, resolution), np.int32)
    xs = x0 + (np.arange(resolution) + 0.5) * max(x1 - x0, 1e-6) \
        / resolution
    ys = y0 + (np.arange(resolution) + 0.5) * max(y1 - y0, 1e-6) \
        / resolution
    gx, gy = np.meshgrid(xs, ys)
    for poly in polys:
        p = np.asarray(poly, np.float64).reshape(-1, 2)
        inside = np.zeros_like(gx, bool)
        j = len(p) - 1
        for k in range(len(p)):
            xi, yi = p[k]
            xj, yj = p[j]
            cond = ((yi > gy) != (yj > gy)) & (
                gx < (xj - xi) * (gy - yi) / (yj - yi + 1e-12) + xi)
            inside ^= cond
            j = k
        m |= inside.astype(np.int32)
    return m


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         rois_num=None, gt_count=None):
    """Mask-RCNN mask targets (reference detection.py:2746 over
    mask_util.cc): for each fg roi, rasterize its matched instance's
    polygons cropped to the roi at resolution^2.

    gt_segms: per-image list of per-instance lists of polygons (the
    3-level-LoD analogue).  Returns (mask_rois, roi_has_mask_int32,
    mask_int32) with mask rows flattened to num_classes*res^2 like the
    reference (one-hot over the fg class)."""
    if isinstance(rois, (list, tuple)):
        roi_list = [_np(r).reshape(-1, 4) for r in rois]
    else:
        rv = _np(rois).reshape(-1, 4)
        rn = _np(rois_num).astype(np.int64).reshape(-1) \
            if rois_num is not None else np.asarray([len(rv)])
        ofs = np.concatenate([[0], np.cumsum(rn)])
        roi_list = [rv[ofs[i]:ofs[i + 1]] for i in range(len(rn))]
    lab = _np(labels_int32).reshape(-1)
    gts_boxes = None
    out_rois, out_has, out_masks, pos = [], [], [], 0
    for i, rois_i in enumerate(roi_list):
        segms_i = gt_segms[i]
        # match each fg roi to the gt instance with max IoU of its bbox
        gt_bboxes = []
        for inst in segms_i:
            allp = np.concatenate([np.asarray(p, np.float64).reshape(-1, 2)
                                   for p in inst]) if inst else \
                np.zeros((1, 2))
            gt_bboxes.append([allp[:, 0].min(), allp[:, 1].min(),
                              allp[:, 0].max(), allp[:, 1].max()])
        gt_bboxes = np.asarray(gt_bboxes, np.float32).reshape(-1, 4)
        for r in rois_i:
            li = int(lab[pos]); pos += 1
            if li <= 0 or len(segms_i) == 0:
                continue
            iou = _iou_np(r[None], gt_bboxes)[0]
            inst = segms_i[int(iou.argmax())]
            m = _rasterize_polygons(inst, r[0], r[1], r[2], r[3],
                                    resolution)
            flat = np.full((num_classes, resolution, resolution), -1,
                           np.int32)
            flat[li] = m
            out_rois.append(r)
            out_has.append(1)
            out_masks.append(flat.reshape(-1))
    if not out_rois:
        return (Tensor(jnp.zeros((0, 4), jnp.float32), stop_gradient=True),
                Tensor(jnp.zeros((0,), jnp.int32), stop_gradient=True),
                Tensor(jnp.zeros((0, num_classes * resolution ** 2),
                                 jnp.int32), stop_gradient=True))
    return (Tensor(jnp.asarray(np.stack(out_rois)), stop_gradient=True),
            Tensor(jnp.asarray(np.asarray(out_has, np.int32)),
                   stop_gradient=True),
            Tensor(jnp.asarray(np.stack(out_masks)), stop_gradient=True))


def retinanet_detection_output(bboxes, scores, anchors, im_info=None,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet serving head (reference detection.py:3104): per FPN level
    decode the top candidates against that level's anchors, concat levels,
    then per-image multiclass NMS.  Returns ((B, keep_top_k, 6) padded
    rows [label, score, x1..y2], valid counts) — the repo's fixed-extent
    NMS contract."""
    from .ops import multiclass_nms_padded
    n = unwrap(bboxes[0]).shape[0]
    outs, counts = [], []
    for i in range(n):
        decoded_all, score_all = [], []
        for lvl in range(len(bboxes)):
            deltas = _np(bboxes[lvl])[i]                  # (A, 4)
            sc = _np(scores[lvl])[i]                      # (A, C)
            anc = _np(anchors[lvl]).reshape(-1, 4)
            best = sc.max(axis=1)
            keep = np.nonzero(best >= score_threshold)[0]
            keep = keep[np.argsort(-best[keep])][:nms_top_k]
            aw = anc[keep, 2] - anc[keep, 0]
            ah = anc[keep, 3] - anc[keep, 1]
            acx = anc[keep, 0] + aw / 2
            acy = anc[keep, 1] + ah / 2
            d = deltas[keep]
            cx = d[:, 0] * aw + acx
            cy = d[:, 1] * ah + acy
            w = np.exp(d[:, 2]) * aw
            h = np.exp(d[:, 3]) * ah
            dec = np.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                            cy + h / 2], axis=1)
            decoded_all.append(dec)
            score_all.append(sc[keep])
        dec = np.concatenate(decoded_all).astype(np.float32)
        sc = np.concatenate(score_all).astype(np.float32)
        rows, cnt = multiclass_nms_padded(
            Tensor(jnp.asarray(dec)), Tensor(jnp.asarray(sc.T)),
            score_threshold, nms_top_k, keep_top_k,
            nms_threshold=nms_threshold, background_label=-1)
        outs.append(unwrap(rows))
        counts.append(unwrap(cnt))
    return (Tensor(jnp.stack(outs), stop_gradient=True),
            Tensor(jnp.stack(counts), stop_gradient=True))


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST locality-aware NMS (reference detection.py:3414): first merge
    CONSECUTIVE boxes whose IoU exceeds the threshold by score-weighted
    averaging, then standard multiclass NMS.  Host-side serving op."""
    from .ops import multiclass_nms
    bv = _np(bboxes)
    sv = _np(scores)
    if bv.ndim == 3:
        bv, sv = bv[0], sv[0]
    c = sv.shape[0]
    best_cls = sv.argmax(axis=0)
    best_score = sv.max(axis=0)
    # merge pass over the box list order (EAST's row-major geometry):
    # consecutive boxes above the IoU threshold fuse by score-weighted
    # average, accumulating score (the LANMS trick)
    out_b, out_s, out_c = [], [], []
    cur_box, cur_score, cur_cls = None, 0.0, 0
    for j in range(len(bv)):
        b, s = bv[j], float(best_score[j])
        if cur_box is not None and _iou_np(
                b[None], cur_box[None])[0, 0] > nms_threshold:
            w = cur_score + s
            cur_box = (cur_box * cur_score + b * s) / max(w, 1e-10)
            cur_score = w
        else:
            if cur_box is not None:
                out_b.append(cur_box)
                out_s.append(cur_score)
                out_c.append(cur_cls)
            cur_box, cur_score, cur_cls = b.copy(), s, int(best_cls[j])
    if cur_box is not None:
        out_b.append(cur_box)
        out_s.append(cur_score)
        out_c.append(cur_cls)
    mb = np.asarray(out_b, np.float32).reshape(-1, 4)
    ms = np.clip(np.asarray(out_s, np.float32), 0, 1.0)
    full_scores = np.zeros((c, len(mb)), np.float32)
    for j in range(len(mb)):
        full_scores[out_c[j], j] = ms[j]
    return multiclass_nms(Tensor(jnp.asarray(mb)),
                          Tensor(jnp.asarray(full_scores)),
                          score_threshold, nms_top_k, keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    """Cascade-RCNN decode+assign (reference detection.py:3795 over
    box_decoder_and_assign_op): decode per-class deltas (M, 4C) against
    the priors, clip, then pick each row's argmax-class box.  Returns
    (decoded (M, 4C), assigned (M, 4)); fully on-device."""
    def raw(pb, pv, tb, sc):
        m = pb.shape[0]
        c = tb.shape[1] // 4
        pw = pb[:, 2] - pb[:, 0] + 1.0
        ph = pb[:, 3] - pb[:, 1] + 1.0
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        d = tb.reshape(m, c, 4) * pv[:, None, :]
        cx = d[..., 0] * pw[:, None] + pcx[:, None]
        cy = d[..., 1] * ph[:, None] + pcy[:, None]
        w = jnp.exp(jnp.minimum(d[..., 2], box_clip)) * pw[:, None]
        h = jnp.exp(jnp.minimum(d[..., 3], box_clip)) * ph[:, None]
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=-1)
        best = jnp.argmax(sc, axis=1)
        assigned = jnp.take_along_axis(
            dec, best[:, None, None].astype(jnp.int32).repeat(4, -1),
            axis=1)[:, 0]
        return dec.reshape(m, c * 4), assigned

    return dispatch("box_decoder_and_assign", raw, prior_box,
                    prior_box_var, target_box, box_score)


def polygon_box_transform(input, name=None):  # noqa: A002
    """EAST geometry-map transform (reference
    polygon_box_transform_op.cc:15): even channels become 4*w - v, odd
    channels 4*h - v (quad offsets to absolute coords)."""
    def raw(x):
        n, c, h, w = x.shape
        ws = jnp.arange(w, dtype=x.dtype) * 4
        hs = jnp.arange(h, dtype=x.dtype) * 4
        even = ws[None, None, None, :] - x
        odd = hs[None, None, :, None] - x
        is_even = (jnp.arange(c) % 2 == 0)[None, :, None, None]
        return jnp.where(is_even, even, odd)

    return dispatch("polygon_box_transform", raw, input)


def roi_perspective_transform(input, rois, transformed_height,  # noqa: A002
                              transformed_width, spatial_scale=1.0,
                              rois_num=None, name=None):
    """Perspective-warp quad rois to rectangles (reference
    detection.py:2502 over roi_perspective_transform_op): per roi an
    8-point quad (x1..y4) maps to a (th, tw) rectangle via its
    homography; sampling is bilinear on the feature map.

    TPU-native split: the 3x3 homographies solve host-side (rois are
    data), the warp gather runs as one dispatched vmapped bilinear sample
    so gradients flow into `input`.  Returns (out (R, C, th, tw),
    mask (R, 1, th, tw), transform_matrix (R, 9))."""
    th, tw = int(transformed_height), int(transformed_width)
    rv = _np(rois).reshape(-1, 8) * spatial_scale
    n_roi = rv.shape[0]
    xv = unwrap(input)
    _, cch, hgt, wid = xv.shape
    if rois_num is not None:
        rn = _np(rois_num).astype(np.int64).reshape(-1)
        batch_ids = np.repeat(np.arange(len(rn)), rn)
    else:
        batch_ids = np.zeros(n_roi, np.int64)

    mats = np.zeros((n_roi, 9), np.float64)
    for r in range(n_roi):
        quad = rv[r].reshape(4, 2)  # (x1,y1)..(x4,y4) clockwise from tl
        dst = np.asarray([[0, 0], [tw - 1, 0], [tw - 1, th - 1],
                          [0, th - 1]], np.float64)
        # solve the 8-dof homography dst -> src (so sampling pulls)
        a = []
        b = []
        for (dx, dy), (sx, sy) in zip(dst, quad):
            a.append([dx, dy, 1, 0, 0, 0, -sx * dx, -sx * dy])
            b.append(sx)
            a.append([0, 0, 0, dx, dy, 1, -sy * dx, -sy * dy])
            b.append(sy)
        try:
            sol = np.linalg.solve(np.asarray(a), np.asarray(b))
        except np.linalg.LinAlgError:
            sol = np.zeros(8)
        mats[r] = np.concatenate([sol, [1.0]])

    gx, gy = np.meshgrid(np.arange(tw, dtype=np.float64),
                         np.arange(th, dtype=np.float64))
    ones = np.ones_like(gx)
    grid = np.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # (th*tw, 3)
    src = np.einsum("rij,pj->rpi", mats.reshape(n_roi, 3, 3), grid)
    denom = np.where(np.abs(src[..., 2]) < 1e-12, 1e-12, src[..., 2])
    sx = (src[..., 0] / denom).reshape(n_roi, th, tw)
    sy = (src[..., 1] / denom).reshape(n_roi, th, tw)
    valid = ((sx >= 0) & (sx <= wid - 1) & (sy >= 0) & (sy <= hgt - 1))

    sxj = jnp.asarray(np.clip(sx, 0, wid - 1), jnp.float32)
    syj = jnp.asarray(np.clip(sy, 0, hgt - 1), jnp.float32)
    bid = jnp.asarray(batch_ids, jnp.int32)
    vj = jnp.asarray(valid)

    def raw(x):
        def one(b, fx, fy, ok):
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1 = jnp.minimum(x0 + 1, wid - 1)
            y1 = jnp.minimum(y0 + 1, hgt - 1)
            lx = fx - x0
            ly = fy - y0
            f = x[b]                                      # (C, H, W)
            v = (f[:, y0, x0] * (1 - ly) * (1 - lx)
                 + f[:, y0, x1] * (1 - ly) * lx
                 + f[:, y1, x0] * ly * (1 - lx)
                 + f[:, y1, x1] * ly * lx)
            return jnp.where(ok[None], v, 0.0)

        return jax.vmap(one)(bid, sxj, syj, vj)

    out = dispatch("roi_perspective_transform", raw, input)
    mask = Tensor(jnp.asarray(valid[:, None].astype(np.int32)),
                  stop_gradient=True)
    return out, mask, Tensor(jnp.asarray(mats.astype(np.float32)),
                             stop_gradient=True)
