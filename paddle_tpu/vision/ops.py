"""paddle.vision.ops — detection operator family.

Reference: paddle/fluid/operators/detection/ (66 CUDA/C++ kernels) surfaced
through python/paddle/vision/ops.py.  TPU-native rules applied throughout:
- fixed output extents (padded with -1/0) instead of the reference's
  LoD-dynamic outputs — NMS returns `max_out` slots with a valid count so
  everything jits with static shapes;
- suppression/argmax loops are `lax.fori_loop`s over masked dense tensors
  (no data-dependent Python control flow);
- roi_align/roi_pool gather with bilinear weights via vectorized
  `take`-style indexing that XLA fuses, not per-pixel scalar loops.

Implemented: yolo_box, yolo_loss, prior_box, anchor_generator, box_coder,
iou_similarity/box_iou, box_clip, nms(+nms_padded),
multiclass_nms(+_padded), distribute/collect_fpn_proposals, roi_align,
roi_pool, deform_conv2d/DeformConv2D, generate_proposals,
bipartite_match, target_assign.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.op import dispatch
from ..core.tensor import Tensor, unwrap

__all__ = [
    "yolo_box", "yolo_loss", "prior_box", "anchor_generator", "box_coder",
    "iou_similarity", "box_iou", "box_clip", "nms", "multiclass_nms",
    "distribute_fpn_proposals", "roi_align", "roi_pool", "deform_conv2d",
    "DeformConv2D", "generate_proposals", "nms_padded",
    "multiclass_nms_padded", "bipartite_match", "target_assign",
    "collect_fpn_proposals", "density_prior_box", "ssd_loss",
    "detection_output", "psroi_pool", "prroi_pool",
    "deformable_roi_pooling", "matrix_nms",
]


# ---------------------------------------------------------------------------
# box decode / anchors


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head output (reference: detection/yolo_box_op).

    x: (N, an_num*(5+class_num), H, W); img_size: (N, 2) [h, w].
    Returns boxes (N, H*W*an_num, 4) in x1y1x2y2 image coords and scores
    (N, H*W*an_num, class_num); predictions with objectness below
    conf_thresh have score 0 (the reference zeroes them the same way)."""
    an_num = len(anchors) // 2
    anchors_a = jnp.asarray(anchors, jnp.float32).reshape(an_num, 2)

    def raw(x, img_size):
        n, c, h, w = x.shape
        x = x.reshape(n, an_num, 5 + class_num, h, w)
        grid_x = jnp.arange(w, dtype=jnp.float32)
        grid_y = jnp.arange(h, dtype=jnp.float32)
        sig = jax.nn.sigmoid
        bias = 0.5 * (scale_x_y - 1.0)
        cx = (sig(x[:, :, 0]) * scale_x_y - bias + grid_x[None, None, None, :]) / w
        cy = (sig(x[:, :, 1]) * scale_x_y - bias + grid_y[None, None, :, None]) / h
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        bw = jnp.exp(x[:, :, 2]) * anchors_a[None, :, 0, None, None] / input_w
        bh = jnp.exp(x[:, :, 3]) * anchors_a[None, :, 1, None, None] / input_h
        obj = sig(x[:, :, 4])
        cls = sig(x[:, :, 5:])
        img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * img_w
        y1 = (cy - bh / 2) * img_h
        x2 = (cx + bw / 2) * img_w
        y2 = (cy + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        keep = (obj >= conf_thresh).astype(x.dtype)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
        scores = cls * (obj * keep)[:, :, None]
        # (N, an, H, W, ...) -> (N, H*W*an, ...) matching the reference's
        # an-major-within-cell order? reference orders (an, h, w) row-major.
        boxes = boxes.reshape(n, an_num * h * w, 4)
        scores = jnp.moveaxis(scores, 2, -1).reshape(
            n, an_num * h * w, class_num)
        return boxes, scores
    return dispatch("yolo_box", raw, x, img_size)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes per feature-map cell (reference:
    detection/prior_box_op).  Returns (boxes (H, W, P, 4) normalized
    x1y1x2y2, variances same shape)."""
    ih, iw = unwrap(input).shape[-2:]
    imh, imw = unwrap(image).shape[-2:]
    step_w = steps[0] or float(imw) / iw
    step_h = steps[1] or float(imh) / ih

    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))

    whs = []  # (w, h) of each prior, in pixels
    for ms in min_sizes:
        ms = float(ms)
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                mx = float(max_sizes[min_sizes.index(ms)])
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                mx = float(max_sizes[min_sizes.index(ms)])
                whs.append((math.sqrt(ms * mx), math.sqrt(ms * mx)))
    whs_a = jnp.asarray(whs, jnp.float32)  # (P, 2)

    cx = (jnp.arange(iw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(ih, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)  # (H, W)
    half_w = whs_a[:, 0] / 2 / imw
    half_h = whs_a[:, 1] / 2 / imh
    ncx = (cxg / imw)[:, :, None]
    ncy = (cyg / imh)[:, :, None]
    boxes = jnp.stack([ncx - half_w, ncy - half_h, ncx + half_w,
                       ncy + half_h], axis=-1)  # (H, W, P, 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    return Tensor(boxes), Tensor(var)


def anchor_generator(input, anchor_sizes, aspect_ratios, variances,
                     stride, offset=0.5, name=None):
    """RCNN anchors (reference: detection/anchor_generator_op).  Returns
    (anchors (H, W, A, 4) in input-image pixels, variances)."""
    ih, iw = unwrap(input).shape[-2:]
    whs = []
    for ar in aspect_ratios:
        for sz in anchor_sizes:
            area = float(sz) * float(sz)
            w = math.sqrt(area / ar)
            whs.append((w, w * ar))
    whs_a = jnp.asarray(whs, jnp.float32)
    cx = (jnp.arange(iw, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(ih, dtype=jnp.float32) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    half = whs_a / 2
    boxes = jnp.stack(
        [cxg[:, :, None] - half[:, 0], cyg[:, :, None] - half[:, 1],
         cxg[:, :, None] + half[:, 0], cyg[:, :, None] + half[:, 1]],
        axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return Tensor(boxes), Tensor(var)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode RCNN box deltas (reference: detection/box_coder_op).

    encode: target (M, 4) vs priors (N, 4) -> (M, N, 4) deltas.
    decode: deltas (N, M, 4) with priors broadcast on `axis`."""
    norm = 0.0 if box_normalized else 1.0

    def center_form(b):
        w = b[..., 2] - b[..., 0] + norm
        h = b[..., 3] - b[..., 1] + norm
        return (b[..., 0] + w / 2, b[..., 1] + h / 2, w, h)

    def raw(prior, var, target):
        pcx, pcy, pw, ph = center_form(prior)
        if code_type == "encode_center_size":
            tcx, tcy, tw, th = center_form(target)
            # (M, N): target rows against prior columns
            dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            dy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            dw = jnp.log(tw[:, None] / pw[None, :])
            dh = jnp.log(th[:, None] / ph[None, :])
            out = jnp.stack([dx, dy, dw, dh], axis=-1)
            if var is not None:
                out = out / var[None, :, :]
            return out
        # decode: deltas (N, M, 4); priors along `axis`
        d = target
        if var is not None:
            vexp = var[:, None, :] if axis == 0 else var[None, :, :]
            d = d * vexp
        exp = (lambda a: a[:, None]) if axis == 0 else (lambda a: a[None, :])
        cx = d[..., 0] * exp(pw) + exp(pcx)
        cy = d[..., 1] * exp(ph) + exp(pcy)
        w = jnp.exp(d[..., 2]) * exp(pw)
        h = jnp.exp(d[..., 3]) * exp(ph)
        return jnp.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - norm, cy + h / 2 - norm], axis=-1)
    return dispatch("box_coder", raw, prior_box, prior_box_var, target_box)


# ---------------------------------------------------------------------------
# IoU / NMS


def _iou_matrix(a, b, norm=0.0):
    """(A, 4) x (B, 4) -> (A, B) IoU."""
    area_a = (a[:, 2] - a[:, 0] + norm) * (a[:, 3] - a[:, 1] + norm)
    area_b = (b[:, 2] - b[:, 0] + norm) * (b[:, 3] - b[:, 1] + norm)
    x1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    y1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    x2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    y2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    inter = jnp.clip(x2 - x1 + norm, 0) * jnp.clip(y2 - y1 + norm, 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU (reference: detection/iou_similarity_op)."""
    norm = 0.0 if box_normalized else 1.0

    def raw(x, y):
        return _iou_matrix(x, y, norm)
    return dispatch("iou_similarity", raw, x, y)


box_iou = iou_similarity


def box_clip(input, im_info, name=None):
    """Clip boxes to image extent (reference: detection/box_clip_op).
    im_info: (h, w) or (h, w, scale) — with a scale, boxes are clipped to
    the ORIGINAL image round(h/scale) x round(w/scale) like the reference
    kernel."""
    has_scale = unwrap(im_info).shape[-1] >= 3

    def raw(b, info):
        h, w = info[0], info[1]
        if has_scale:
            h = jnp.round(h / info[2])
            w = jnp.round(w / info[2])
        return jnp.stack([jnp.clip(b[..., 0], 0, w - 1),
                          jnp.clip(b[..., 1], 0, h - 1),
                          jnp.clip(b[..., 2], 0, w - 1),
                          jnp.clip(b[..., 3], 0, h - 1)], axis=-1)
    return dispatch("box_clip", raw, input, im_info)


def _greedy_suppress(iou_sorted, init_keep, iou_threshold):
    """Score-descending greedy suppression over a (n, n) IoU matrix whose
    rows/cols are already sorted by score: slot i survives iff it starts
    eligible (init_keep) and no higher-scored KEPT slot overlaps it above
    the threshold.  Single source of truth for nms / nms_padded /
    multiclass_nms_padded."""
    n = iou_sorted.shape[0]

    def body(i, keep):
        higher_kept = jnp.logical_and(jnp.arange(n) < i, keep)
        sup = jnp.any(jnp.logical_and(higher_kept,
                                      iou_sorted[i] > iou_threshold))
        return keep.at[i].set(jnp.logical_and(init_keep[i],
                                              jnp.logical_not(sup)))

    return jax.lax.fori_loop(0, n, body, init_keep)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy hard NMS (reference: detection/nms_op; paddle.vision.ops.nms).

    Returns kept indices sorted by descending score.  TPU-native: the
    suppression loop is a fixed-trip `lax.fori_loop` over a mask; the
    (static-size) index vector is then compacted host-side.  When
    `category_idxs` is given, suppression is per category (boxes of
    different categories never suppress each other)."""
    from jax import lax
    bv = unwrap(boxes)
    n = bv.shape[0]
    sv = unwrap(scores) if scores is not None else jnp.arange(
        n, 0, -1, dtype=jnp.float32)
    cv = unwrap(category_idxs) if category_idxs is not None else None

    iou = _iou_matrix(bv, bv)
    if cv is not None:
        iou = jnp.where(cv[:, None] == cv[None, :], iou, 0.0)
    order = jnp.argsort(-sv)
    iou_o = iou[order][:, order]  # sorted by descending score
    keep = _greedy_suppress(iou_o, jnp.ones((n,), bool), iou_threshold)
    order_np = np.asarray(jax.device_get(order))
    keep_np = np.asarray(jax.device_get(keep))
    kept = order_np[keep_np]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(jnp.asarray(kept, jnp.int32), stop_gradient=True)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k,
                   keep_top_k, nms_threshold=0.3, normalized=True,
                   background_label=-1, name=None):
    """Per-class NMS + global top-k (reference:
    detection/multiclass_nms_op).  bboxes (N, 4), scores (C, N).
    Returns (out (keep_top_k, 6) rows [label, score, x1, y1, x2, y2]
    padded with -1, valid_count).

    HOST-side eval/postprocessing path (per-class Python loop + numpy
    compaction) — it cannot run inside jit.  For a jitted eval loop or
    on-device serving use `multiclass_nms_padded`, which has the same
    selection semantics with static shapes throughout."""
    bv = np.asarray(jax.device_get(unwrap(bboxes)))
    sv = np.asarray(jax.device_get(unwrap(scores)))
    c, n = sv.shape
    rows = []
    for ci in range(c):
        if ci == background_label:
            continue
        # reference order: threshold -> top nms_top_k candidates -> NMS
        cand = np.nonzero(sv[ci] >= score_threshold)[0]
        if cand.size == 0:
            continue
        cand = cand[np.argsort(-sv[ci, cand])][:nms_top_k]
        keep = nms(Tensor(jnp.asarray(bv[cand])), nms_threshold,
                   Tensor(jnp.asarray(sv[ci, cand])))
        for i in cand[np.asarray(keep.numpy())]:
            rows.append((float(ci), float(sv[ci, i])) + tuple(
                float(v) for v in bv[i]))
    rows.sort(key=lambda r: -r[1])
    rows = rows[:keep_top_k]
    out = np.full((keep_top_k, 6), -1.0, np.float32)
    for i, r in enumerate(rows):
        out[i] = r
    return Tensor(jnp.asarray(out), stop_gradient=True), len(rows)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Assign RoIs to FPN levels (reference:
    detection/distribute_fpn_proposals_op): level = floor(refer_level +
    log2(sqrt(area)/refer_scale)), clipped to [min, max]."""
    rv = unwrap(fpn_rois)
    w = rv[:, 2] - rv[:, 0]
    h = rv[:, 3] - rv[:, 1]
    scale = jnp.sqrt(jnp.clip(w * h, 1e-10))
    lvl = jnp.floor(refer_level + jnp.log2(scale / refer_scale + 1e-10))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    lvl_np = np.asarray(jax.device_get(lvl))
    img_of = None
    if rois_num is not None:  # per-image roi counts -> per-level counts
        bn = np.asarray(jax.device_get(unwrap(rois_num))).astype(np.int64)
        img_of = np.repeat(np.arange(len(bn)), bn)
    outs, index, level_rois_num = [], [], []
    for l in range(min_level, max_level + 1):
        idx = np.nonzero(lvl_np == l)[0]
        outs.append(Tensor(jnp.asarray(rv[jnp.asarray(idx)]))
                    if len(idx) else Tensor(jnp.zeros((0, 4), rv.dtype)))
        index.extend(idx.tolist())
        if img_of is not None:
            level_rois_num.append(Tensor(jnp.asarray(
                np.bincount(img_of[idx], minlength=len(bn)).astype(
                    np.int32)), stop_gradient=True))
    # restore[original_idx] = row of that roi in the concatenated outputs
    restore = np.zeros(len(lvl_np), np.int64)
    if index:
        restore[np.asarray(index, np.int64)] = np.arange(len(index))
    restore_t = Tensor(jnp.asarray(restore), stop_gradient=True)
    if rois_num is not None:
        return outs, restore_t, level_rois_num
    return outs, restore_t


# ---------------------------------------------------------------------------
# RoI pooling


def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear sampling (reference: detection/roi_align_op).

    x: (N, C, H, W); boxes: (R, 4) in input-image coords; boxes_num: (N,)
    rois per image (defaults to all on image 0).  Output (R, C, P, P)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    xv = unwrap(x)
    bv = unwrap(boxes)
    n_img, c, h, w = xv.shape
    r = bv.shape[0]
    if boxes_num is None:
        img_of = jnp.zeros((r,), jnp.int32)
    else:
        bn = np.asarray(jax.device_get(unwrap(boxes_num))).astype(np.int64)
        img_of = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    if sampling_ratio > 0:
        sr = sampling_ratio
    else:
        # reference uses ceil(bin_size) samples PER RoI — a dynamic extent
        # XLA can't compile.  Static stand-in: size the shared grid for the
        # batch's largest bin (boxes are concrete in eager dispatch),
        # capped at 8; with traced boxes fall back to 2.
        try:
            bnp = np.asarray(jax.device_get(bv)).astype(np.float64)
            max_bin = max(float(np.max((bnp[:, 2] - bnp[:, 0])
                                       * spatial_scale / pw)),
                          float(np.max((bnp[:, 3] - bnp[:, 1])
                                       * spatial_scale / ph)), 1.0) \
                if len(bnp) else 1.0
            sr = int(min(max(math.ceil(max_bin), 1), 8))
        except Exception:
            sr = 2

    def raw(xv, bv, img_of):
        off = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - off
        y1 = bv[:, 1] * spatial_scale - off
        x2 = bv[:, 2] * spatial_scale - off
        y2 = bv[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (R, P*sr) per axis
        iy = (jnp.arange(ph * sr) + 0.5) / sr
        ix = (jnp.arange(pw * sr) + 0.5) / sr
        sy = y1[:, None] + bin_h[:, None] * iy[None, :]  # (R, ph*sr)
        sx = x1[:, None] + bin_w[:, None] * ix[None, :]  # (R, pw*sr)

        def bilinear(img, yy, xx):
            # img (C, H, W); yy (hs,), xx (ws,) -> (C, hs, ws)
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1i = jnp.clip(y0 + 1, 0, h - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, w - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy1 = jnp.clip(yy, 0, h - 1) - y0
            wx1 = jnp.clip(xx, 0, w - 1) - x0
            wy0, wx0 = 1 - wy1, 1 - wx1
            # outside-image samples contribute 0 (reference semantics)
            vy = jnp.logical_and(yy > -1.0, yy < h)
            vx = jnp.logical_and(xx > -1.0, xx < w)
            v = jnp.logical_and(vy[:, None], vx[None, :])
            g = (img[:, y0i[:, None], x0i[None, :]] * (wy0[:, None] * wx0[None, :])
                 + img[:, y0i[:, None], x1i[None, :]] * (wy0[:, None] * wx1[None, :])
                 + img[:, y1i[:, None], x0i[None, :]] * (wy1[:, None] * wx0[None, :])
                 + img[:, y1i[:, None], x1i[None, :]] * (wy1[:, None] * wx1[None, :]))
            return jnp.where(v[None], g, 0.0)

        def per_roi(ri):
            img = xv[img_of[ri]]
            g = bilinear(img, sy[ri], sx[ri])  # (C, ph*sr, pw*sr)
            return g.reshape(c, ph, sr, pw, sr).mean((2, 4))
        return jax.vmap(per_roi)(jnp.arange(r))
    return dispatch("roi_align", raw, x, boxes,
                    Tensor(img_of, stop_gradient=True))


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
             name=None):
    """RoIPool max pooling (reference: detection/roi_pool_op)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    xv = unwrap(x)
    bv = unwrap(boxes)
    n_img, c, h, w = xv.shape
    r = bv.shape[0]
    if boxes_num is None:
        img_of = jnp.zeros((r,), jnp.int32)
    else:
        bn = np.asarray(jax.device_get(unwrap(boxes_num))).astype(np.int64)
        img_of = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)

    def raw(xv, bv, img_of):
        x1 = jnp.round(bv[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bv[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(bv[:, 2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(bv[:, 3] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)

        ys = jnp.arange(h)
        xs = jnp.arange(w)

        def per_roi(ri):
            # reference bins OVERLAP (hstart=floor(i*rh/ph),
            # hend=ceil((i+1)*rh/ph)): a boundary pixel belongs to both
            # adjacent bins, so each of the ph*pw bins takes its own
            # masked max (static unroll; the pooled grid is tiny)
            img = xv[img_of[ri]]
            iny = jnp.logical_and(ys >= y1[ri], ys <= y2[ri])
            inx = jnp.logical_and(xs >= x1[ri], xs <= x2[ri])
            rows = []
            for i in range(ph):
                hs = y1[ri] + (i * rh) // ph
                he = y1[ri] + -((-(i + 1) * rh) // ph)  # ceil div
                my = jnp.logical_and(jnp.logical_and(ys >= hs, ys < he),
                                     iny)
                cols = []
                for j in range(pw):
                    ws = x1[ri] + (j * rw) // pw
                    we = x1[ri] + -((-(j + 1) * rw) // pw)
                    mx = jnp.logical_and(jnp.logical_and(xs >= ws, xs < we),
                                         inx)
                    m = jnp.logical_and(my[:, None], mx[None, :])
                    v = jnp.where(m[None], img, -jnp.inf).max((1, 2))
                    cols.append(jnp.where(jnp.isfinite(v), v, 0.0))
                rows.append(jnp.stack(cols, -1))
            return jnp.stack(rows, -2)  # (C, ph, pw)
        return jax.vmap(per_roi)(jnp.arange(r))
    return dispatch("roi_pool", raw, x, boxes,
                    Tensor(img_of, stop_gradient=True))


# ---------------------------------------------------------------------------
# deformable convolution


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference: paddle/vision/ops.py:394
    deform_conv2d backed by operators/deformable_conv_op.cu).

    x: (N, Cin, H, W); offset: (N, 2*dg*kh*kw, Hout, Wout) with channel
    layout [dy0, dx0, dy1, dx1, ...]; mask (v2): (N, dg*kh*kw, Hout, Wout)
    or None (v1).  weight: (Cout, Cin//groups, kh, kw).

    TPU-native: instead of the reference's per-position im2col CUDA kernel,
    the sampled patch tensor is built with one vectorized bilinear gather
    (4 corner `take`s weighted and summed — all MXU/VPU friendly, fully
    differentiable through jax) and contracted with the weight in a single
    einsum so XLA maps it onto the MXU."""
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def raw(xv, ov, wv, mv, bv):
        n, cin, h, w = xv.shape
        cout, cin_g, kh, kw = wv.shape
        sh, sw = stride
        ph, pw = padding
        dh, dw = dilation
        dg = deformable_groups
        hout = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        wout = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        k = kh * kw

        # base sampling grid (k, Hout, Wout)
        ky = (jnp.arange(k) // kw) * dh
        kx = (jnp.arange(k) % kw) * dw
        oy = jnp.arange(hout) * sh - ph
        ox = jnp.arange(wout) * sw - pw
        base_y = jnp.broadcast_to(
            (ky[:, None, None] + oy[None, :, None]),
            (k, hout, wout)).astype(jnp.float32)
        base_x = jnp.broadcast_to(
            (kx[:, None, None] + ox[None, None, :]),
            (k, hout, wout)).astype(jnp.float32)

        # learned offsets: (N, dg, k, 2, Hout, Wout) — [dy, dx] pairs
        off = ov.reshape(n, dg, k, 2, hout, wout)
        sy = base_y[None, None] + off[:, :, :, 0]  # (N, dg, k, Hout, Wout)
        sx = base_x[None, None] + off[:, :, :, 1]

        y0 = jnp.floor(sy)
        x0 = jnp.floor(sx)
        ly = sy - y0
        lx = sx - x0

        cg = cin // dg
        xg = xv.reshape(n, dg, cg, h * w)  # channels grouped by deform group

        def corner(yy, xx, wgt):
            inside = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            # per-group gather: grid (N, dg, k, Hout, Wout) indexes only its
            # own channel group (no dg-fold over-gather)
            flat = (yc * w + xc).reshape(n, dg, 1, k * hout * wout)
            g = jnp.take_along_axis(
                xg, jnp.broadcast_to(flat, (n, dg, cg, k * hout * wout)),
                axis=3)
            g = g.reshape(n, dg, cg, k, hout, wout)
            wgt = jnp.where(inside, wgt, 0.0)  # zero-pad outside
            return g * wgt.reshape(n, dg, 1, k, hout, wout)

        patches = (corner(y0, x0, (1 - ly) * (1 - lx))
                   + corner(y0, x0 + 1, (1 - ly) * lx)
                   + corner(y0 + 1, x0, ly * (1 - lx))
                   + corner(y0 + 1, x0 + 1, ly * lx))
        if mv is not None:
            patches = patches * mv.reshape(n, dg, 1, k, hout, wout)
        patches = patches.reshape(n, cin, k, hout, wout)
        # grouped contraction with the weight on the MXU
        patches = patches.reshape(n, groups, cin // groups, k, hout, wout)
        wg = wv.reshape(groups, cout // groups, cin_g, k)
        out = jnp.einsum("ngckhw,gock->ngohw", patches, wg)
        out = out.reshape(n, cout, hout, wout)
        if bv is not None:
            out = out + bv.reshape(1, cout, 1, 1)
        return out

    # dispatch flattens None args to empty subtrees, so one call covers the
    # with/without mask/bias cases (grads flow to every supplied tensor)
    return dispatch("deform_conv2d", raw, x, offset, weight, mask, bias)


_deform_layer_cls = None


def _make_deform_layer_cls():
    """Build (once) the DeformConv2D Layer subclass; the Layer import is
    deferred to first use so vision.ops stays importable standalone."""
    global _deform_layer_cls
    if _deform_layer_cls is None:
        from ..nn.layer_base import Layer

        class _DeformConv2D(Layer):
            def __init__(self, in_channels, out_channels, kernel_size,
                         stride=1, padding=0, dilation=1,
                         deformable_groups=1, groups=1, weight_attr=None,
                         bias_attr=None):
                super().__init__()
                ks = ((kernel_size, kernel_size)
                      if isinstance(kernel_size, int) else tuple(kernel_size))
                self._cfg = (stride, padding, dilation, deformable_groups,
                             groups)
                import math as _m
                from ..nn import initializer as I
                std = 1.0 / _m.sqrt(in_channels * ks[0] * ks[1])
                self.weight = self.create_parameter(
                    (out_channels, in_channels // groups, ks[0], ks[1]),
                    weight_attr,
                    default_initializer=I.Uniform(-std, std))
                self.bias = None
                if bias_attr is not False:
                    self.bias = self.create_parameter(
                        (out_channels,), bias_attr, is_bias=True,
                        default_initializer=I.Uniform(-std, std))

            def forward(self, x, offset, mask=None):
                s, p, d, dg, g = self._cfg
                return deform_conv2d(x, offset, self.weight, self.bias,
                                     stride=s, padding=p, dilation=d,
                                     deformable_groups=dg, groups=g,
                                     mask=mask)

        _DeformConv2D.__name__ = "DeformConv2D"
        _DeformConv2D.__qualname__ = "DeformConv2D"
        _deform_layer_cls = _DeformConv2D
    return _deform_layer_cls


class _DeformConv2DMeta(type):
    """Makes `DeformConv2D(...)` construct, and isinstance checks resolve
    against, the lazily-built Layer subclass (one shared class, not one per
    instantiation)."""

    def __call__(cls, *args, **kwargs):
        return _make_deform_layer_cls()(*args, **kwargs)

    def __instancecheck__(cls, obj):
        return isinstance(obj, _make_deform_layer_cls())


class DeformConv2D(metaclass=_DeformConv2DMeta):
    """Layer wrapper for deform_conv2d (reference: paddle/vision/ops.py:594
    DeformConv2D)."""


# ---------------------------------------------------------------------------
# YOLOv3 training loss


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference: paddle/vision/ops.py:28 yolo_loss backed by
    operators/detection/yolov3_loss_op).

    x: (N, mask_num*(5+class_num), H, W) raw head output; gt_box (N, B, 4)
    normalized [cx, cy, w, h]; gt_label (N, B) int; gt_score (N, B) mixup
    weights (default 1).  Returns per-image loss (N,).

    Semantics matched to the reference kernel: per-gt best-anchor assignment
    over ALL anchors (only gts whose best anchor falls in `anchor_mask`
    contribute at this level); sigmoid-CE for x/y/objectness/class, L1 for
    w/h, box losses weighted by (2 - gw*gh); negatives whose best IoU with
    any gt exceeds ignore_thresh are excluded from the objectness loss;
    optional label smoothing (1/class_num).  TPU-native: the per-gt loops
    are a vectorized reduction over the padded gt axis (invalid gts get
    zero weight) — no data-dependent control flow, so the whole loss jits
    and differentiates through `jax.grad`.
    """
    mask_num = len(anchor_mask)
    anchors_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    amask = jnp.asarray(anchor_mask, jnp.int32)

    def sce(logit, label):
        # sigmoid cross-entropy, numerically stable
        return jnp.maximum(logit, 0) - logit * label + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    def raw(xv, gtb, gtl, gts):
        n, c, h, w = xv.shape
        assert c == mask_num * (5 + class_num), "channel/anchor mismatch"
        input_h = downsample_ratio * h
        input_w = downsample_ratio * w
        xv = xv.reshape(n, mask_num, 5 + class_num, h, w).astype(jnp.float32)
        gtb = gtb.astype(jnp.float32)
        bnum = gtb.shape[1]
        valid = (gtb[:, :, 2] > 0) & (gtb[:, :, 3] > 0)  # (N, B)

        # --- best anchor per gt over ALL anchors (w/h IoU, both centered) ---
        gw = gtb[:, :, 2] * input_w   # (N, B) in input pixels
        gh = gtb[:, :, 3] * input_h
        aw = anchors_all[:, 0]        # (A,)
        ah = anchors_all[:, 1]
        inter = jnp.minimum(gw[:, :, None], aw) * jnp.minimum(
            gh[:, :, None], ah)
        union = gw[:, :, None] * gh[:, :, None] + aw * ah - inter
        an_iou = inter / jnp.maximum(union, 1e-10)     # (N, B, A)
        best_an = jnp.argmax(an_iou, axis=-1)          # (N, B)
        # position of best anchor inside this level's mask, or -1
        in_mask = best_an[:, :, None] == amask[None, None, :]  # (N,B,M)
        mask_pos = jnp.where(jnp.any(in_mask, -1),
                             jnp.argmax(in_mask, -1), -1)      # (N, B)
        active = valid & (mask_pos >= 0)

        gi = jnp.clip((gtb[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((gtb[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

        # --- gather predictions at assigned cells: (N, B, 5+C) ---
        nb = jnp.arange(n)[:, None]
        mp = jnp.clip(mask_pos, 0)
        pred = xv[nb, mp, :, gj, gi]                   # (N, B, 5+C)

        tx = gtb[:, :, 0] * w - gi
        ty = gtb[:, :, 1] * h - gj
        best_aw = aw[best_an]
        best_ah = ah[best_an]
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(best_aw, 1e-10), 1e-10))
        th = jnp.log(jnp.maximum(gh / jnp.maximum(best_ah, 1e-10), 1e-10))
        box_scale = 2.0 - gtb[:, :, 2] * gtb[:, :, 3]
        score = gts.astype(jnp.float32)
        wgt = jnp.where(active, score, 0.0)

        loss_box = (sce(pred[:, :, 0], tx) + sce(pred[:, :, 1], ty)
                    + jnp.abs(pred[:, :, 2] - tw)
                    + jnp.abs(pred[:, :, 3] - th)) * box_scale
        loss_box = jnp.sum(loss_box * wgt, axis=1)     # (N,)

        # --- class loss at assigned cells ---
        if use_label_smooth and class_num > 1:
            pos_l = 1.0 - 1.0 / class_num
            neg_l = 1.0 / class_num
        else:
            pos_l, neg_l = 1.0, 0.0
        onehot = jax.nn.one_hot(jnp.clip(gtl, 0), class_num)
        cls_label = onehot * pos_l + (1 - onehot) * neg_l
        loss_cls = jnp.sum(sce(pred[:, :, 5:], cls_label), axis=-1)
        loss_cls = jnp.sum(loss_cls * wgt, axis=1)

        # --- objectness: positives from assignment, ignore high-IoU negs ---
        sig = jax.nn.sigmoid
        bias = 0.5 * (scale_x_y - 1.0)
        grid_x = jnp.arange(w, dtype=jnp.float32)
        grid_y = jnp.arange(h, dtype=jnp.float32)
        px = (sig(xv[:, :, 0]) * scale_x_y - bias + grid_x) / w
        py = (sig(xv[:, :, 1]) * scale_x_y - bias
              + grid_y[:, None]) / h
        pw = jnp.exp(xv[:, :, 2]) * anchors_all[amask, 0][None, :, None,
                                                          None] / input_w
        ph = jnp.exp(xv[:, :, 3]) * anchors_all[amask, 1][None, :, None,
                                                          None] / input_h
        pboxes = jnp.stack([px - pw / 2, py - ph / 2,
                            px + pw / 2, py + ph / 2], -1)  # (N,M,H,W,4)
        gx1 = gtb[:, :, 0] - gtb[:, :, 2] / 2
        gy1 = gtb[:, :, 1] - gtb[:, :, 3] / 2
        gx2 = gtb[:, :, 0] + gtb[:, :, 2] / 2
        gy2 = gtb[:, :, 1] + gtb[:, :, 3] / 2
        pb = pboxes[:, :, :, :, None, :]                    # (N,M,H,W,1,4)
        ix1 = jnp.maximum(pb[..., 0], gx1[:, None, None, None, :])
        iy1 = jnp.maximum(pb[..., 1], gy1[:, None, None, None, :])
        ix2 = jnp.minimum(pb[..., 2], gx2[:, None, None, None, :])
        iy2 = jnp.minimum(pb[..., 3], gy2[:, None, None, None, :])
        inter = jnp.clip(ix2 - ix1, 0) * jnp.clip(iy2 - iy1, 0)
        pa = (pb[..., 2] - pb[..., 0]) * (pb[..., 3] - pb[..., 1])
        ga = (gtb[:, :, 2] * gtb[:, :, 3])[:, None, None, None, :]
        iou = inter / jnp.maximum(pa + ga - inter, 1e-10)
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        best_iou = jnp.max(iou, axis=-1)                    # (N,M,H,W)
        noobj_w = (best_iou <= ignore_thresh).astype(jnp.float32)

        tobj = jnp.zeros((n, mask_num, h, w))
        obj_w = noobj_w
        # scatter positives SEQUENTIALLY over the gt axis so that when two
        # gts land in the same cell the LAST one wins, matching the
        # reference kernel's per-gt loop (a single batched scatter with
        # duplicate indices has unspecified order in XLA).  Inactive entries
        # get an OUT-OF-BOUNDS sentinel (mask_num, not -1: negative indices
        # wrap in jax; mode='drop' only drops genuinely OOB ones).
        mp_s = jnp.where(active, mask_pos, mask_num)
        nbv = jnp.arange(n)

        def scatter_gt(bi, carry):
            tobj, obj_w = carry
            im = jnp.take(mp_s, bi, axis=1)
            ij = jnp.take(gj, bi, axis=1)
            ii = jnp.take(gi, bi, axis=1)
            sc = jnp.take(score, bi, axis=1)
            tobj = tobj.at[nbv, im, ij, ii].set(sc, mode="drop")
            obj_w = obj_w.at[nbv, im, ij, ii].set(1.0, mode="drop")
            return tobj, obj_w

        tobj, obj_w = jax.lax.fori_loop(0, bnum, scatter_gt, (tobj, obj_w))
        loss_obj = jnp.sum(sce(xv[:, :, 4], tobj) * obj_w, axis=(1, 2, 3))

        return loss_box + loss_cls + loss_obj

    gts = gt_score if gt_score is not None else Tensor(
        jnp.ones(unwrap(gt_label).shape, jnp.float32))
    return dispatch("yolo_loss",
                    lambda xv, gtb, gts_: raw(xv, gtb, unwrap(gt_label),
                                              gts_),
                    x, gt_box, gts)


# ---------------------------------------------------------------------------
# RPN proposal generation


def _np_adaptive_nms(boxes, scores, thresh, eta, off):
    """Greedy NMS with the reference's adaptive threshold: after each kept
    box, threshold *= eta while it stays > 0.5 (eta >= 1 => plain NMS)."""
    order = np.argsort(-scores, kind="stable")
    areas = (boxes[:, 2] - boxes[:, 0] + off) * (boxes[:, 3] - boxes[:, 1]
                                                 + off)
    suppressed = np.zeros(len(boxes), bool)
    keep = []
    adaptive = float(thresh)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        x1 = np.maximum(boxes[i, 0], boxes[:, 0])
        y1 = np.maximum(boxes[i, 1], boxes[:, 1])
        x2 = np.minimum(boxes[i, 2], boxes[:, 2])
        y2 = np.minimum(boxes[i, 3], boxes[:, 3])
        inter = np.clip(x2 - x1 + off, 0, None) * np.clip(
            y2 - y1 + off, 0, None)
        iou = inter / np.maximum(areas[i] + areas - inter, 1e-10)
        suppressed |= iou > adaptive
        if eta < 1.0 and adaptive > 0.5:
            adaptive *= eta
    return np.asarray(keep, np.int64)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference: paddle/vision/ops.py
    generate_proposals backed by
    operators/detection/generate_proposals_op.cc).

    scores (N, A, H, W); bbox_deltas (N, 4A, H, W); img_size (N, 2) [h, w];
    anchors (H, W, A, 4) x1y1x2y2; variances (H, W, A, 4).
    Per image: top pre_nms_top_n by score -> delta-decode -> clip ->
    min_size filter (clamped to >= 1 like the reference FilterBoxes; with
    pixel_offset the box center must also lie inside the image) ->
    NMS(nms_thresh, adaptive when eta < 1: the threshold decays by eta
    after each kept box while > 0.5) -> top post_nms_top_n.
    Host-side eval/postprocessing path (like multiclass_nms): returns
    (rois (R, 4), roi_probs (R, 1)[, rois_num (N,)]) with dynamic R.
    """
    sv = np.asarray(jax.device_get(unwrap(scores)))
    dv = np.asarray(jax.device_get(unwrap(bbox_deltas)))
    imv = np.asarray(jax.device_get(unwrap(img_size)))
    av = np.asarray(jax.device_get(unwrap(anchors))).reshape(-1, 4)
    vv = np.asarray(jax.device_get(unwrap(variances))).reshape(-1, 4)
    n, a, h, w = sv.shape
    off = 1.0 if pixel_offset else 0.0

    all_rois, all_probs, nums = [], [], []
    for i in range(n):
        s = sv[i].transpose(1, 2, 0).reshape(-1)          # (H*W*A,)
        d = dv[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s, kind="stable")[:pre_nms_top_n]
        s_i, d_i, an, var = s[order], d[order], av[order], vv[order]
        # decode deltas about anchor centers (variance-scaled)
        aw = an[:, 2] - an[:, 0] + off
        ah = an[:, 3] - an[:, 1] + off
        acx = an[:, 0] + aw * 0.5
        acy = an[:, 1] + ah * 0.5
        cx = var[:, 0] * d_i[:, 0] * aw + acx
        cy = var[:, 1] * d_i[:, 1] * ah + acy
        bw = np.exp(np.minimum(var[:, 2] * d_i[:, 2], np.log(1000. / 16.))) * aw
        bh = np.exp(np.minimum(var[:, 3] * d_i[:, 3], np.log(1000. / 16.))) * ah
        boxes = np.stack([cx - bw * 0.5, cy - bh * 0.5,
                          cx + bw * 0.5 - off, cy + bh * 0.5 - off], axis=1)
        ih, iw = imv[i, 0], imv[i, 1]
        boxes[:, 0] = np.clip(boxes[:, 0], 0, iw - off)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, ih - off)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, iw - off)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, ih - off)
        ws = boxes[:, 2] - boxes[:, 0] + off
        hs = boxes[:, 3] - boxes[:, 1] + off
        ms = max(float(min_size), 1.0)  # reference FilterBoxes clamp
        keep = (ws >= ms) & (hs >= ms)
        if pixel_offset:  # center must lie inside the image
            keep &= ((boxes[:, 0] + ws / 2) <= iw) \
                & ((boxes[:, 1] + hs / 2) <= ih)
        boxes, s_i = boxes[keep], s_i[keep]
        if len(boxes):
            kept = _np_adaptive_nms(boxes, s_i, nms_thresh, eta, off)
            kept = kept[:post_nms_top_n]
            boxes, s_i = boxes[kept], s_i[kept]
        all_rois.append(boxes.astype(np.float32))
        all_probs.append(s_i.astype(np.float32)[:, None])
        nums.append(len(boxes))

    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0)
                              if sum(nums) else np.zeros((0, 4), np.float32)),
                  stop_gradient=True)
    probs = Tensor(jnp.asarray(np.concatenate(all_probs, 0)
                               if sum(nums) else np.zeros((0, 1), np.float32)),
                   stop_gradient=True)
    if return_rois_num:
        return rois, probs, Tensor(jnp.asarray(np.asarray(nums, np.int32)),
                                   stop_gradient=True)
    return rois, probs


# ---------------------------------------------------------------------------
# on-device (jittable) padded NMS variants


def nms_padded(boxes, scores=None, iou_threshold=0.3, max_out=None,
               name=None):
    """Fully on-device NMS with a FIXED output extent — usable inside a
    jitted eval loop or the serving path (the host-compacting `nms` above
    cannot be).  Returns (indices (max_out,) int32, valid_count): kept
    indices sorted by descending score, padded with -1."""
    bv = unwrap(boxes)
    n = bv.shape[0]
    max_out = int(max_out) if max_out is not None else n
    sv = unwrap(scores) if scores is not None else jnp.arange(
        n, 0, -1, dtype=jnp.float32)

    def raw(bv, sv):
        iou = _iou_matrix(bv, bv)
        order = jnp.argsort(-sv)
        iou_o = iou[order][:, order]
        keep = _greedy_suppress(iou_o, jnp.ones((n,), bool), iou_threshold)
        pos = jnp.cumsum(keep) - 1          # output slot per kept box
        slot = jnp.where(keep & (pos < max_out), pos, max_out)
        out = jnp.full((max_out,), -1, jnp.int32).at[slot].set(
            order.astype(jnp.int32), mode="drop")
        count = jnp.minimum(jnp.sum(keep.astype(jnp.int32)), max_out)
        return out, count

    out, count = raw(bv, sv)
    return (Tensor(out, stop_gradient=True),
            Tensor(count, stop_gradient=True))


def _nms_padded_raw(bv, sv, score_threshold, nms_top_k, keep_top_k,
                    nms_threshold, background_label):
    """Single-image padded multiclass NMS body: pure jnp over (N, 4) boxes
    and (C, N) scores so `detection_output` can `jax.vmap` it over the
    batch (one compiled program regardless of B)."""
    c, n = sv.shape
    iou = _iou_matrix(bv, bv)
    topn = min(nms_top_k, n) if nms_top_k and nms_top_k > 0 else n

    def per_class(srow):
        svm = jnp.where(srow >= score_threshold, srow, -jnp.inf)
        order = jnp.argsort(-svm)
        valid_sorted = jnp.isfinite(svm[order]) & (jnp.arange(n) < topn)
        iou_o = iou[order][:, order]
        keep = _greedy_suppress(iou_o, valid_sorted, nms_threshold)
        return jnp.zeros((n,), bool).at[order].set(keep)

    keep_cn = jax.vmap(per_class)(sv)          # (C, N)
    if 0 <= background_label < c:
        keep_cn = keep_cn.at[background_label].set(False)
    flat = jnp.where(keep_cn, sv, -jnp.inf).reshape(-1)
    k = min(keep_top_k, c * n)
    top_s, top_i = jax.lax.top_k(flat, k)
    cls = (top_i // n).astype(jnp.float32)
    bix = top_i % n
    valid = jnp.isfinite(top_s)
    rows = jnp.concatenate(
        [cls[:, None], jnp.where(valid, top_s, -1.0)[:, None],
         bv[bix]], axis=1)
    rows = jnp.where(valid[:, None], rows, -1.0)
    if k < keep_top_k:
        rows = jnp.concatenate(
            [rows, jnp.full((keep_top_k - k, 6), -1.0)], axis=0)
    return rows, jnp.sum(valid.astype(jnp.int32))


def multiclass_nms_padded(bboxes, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold=0.3,
                          background_label=-1, name=None):
    """Jittable multiclass NMS: per-class suppression vmapped on device,
    fixed (keep_top_k, 6) output [label, score, x1, y1, x2, y2] padded with
    -1 rows + valid count.  Same selection semantics as `multiclass_nms`
    (threshold -> per-class top nms_top_k -> NMS -> global top keep_top_k)
    but with static shapes throughout (the TPU-native serving variant)."""
    rows, count = _nms_padded_raw(
        unwrap(bboxes), unwrap(scores), score_threshold, nms_top_k,
        keep_top_k, nms_threshold, background_label)
    return (Tensor(rows, stop_gradient=True),
            Tensor(count, stop_gradient=True))


# ---------------------------------------------------------------------------
# detection training assigners (SSD / FPN training side)


def bipartite_match(dist_matrix, match_type="bipartite", dist_threshold=0.5,
                    name=None):
    """Greedy bipartite matching (reference:
    detection/bipartite_match_op): per batch, repeatedly take the global
    max of the distance matrix (rows = ground-truth entities, cols =
    priors/predictions), binding each row and column at most once; with
    match_type='per_prediction', unmatched columns additionally match
    their argmax row when above dist_threshold.

    dist_matrix: (N, M) similarity (e.g. IoU) — a LIST of matrices for a
    batch.  Returns (match_indices (M,) int32 row index or -1,
    match_dist (M,)).  Host-side training-data prep (like the reference's
    CPU-only kernel)."""
    mats = dist_matrix if isinstance(dist_matrix, (list, tuple)) \
        else [dist_matrix]
    outs_i, outs_d = [], []
    for m in mats:
        dv = np.asarray(jax.device_get(unwrap(m))).astype(np.float64)
        n, mm = dv.shape
        match_idx = np.full((mm,), -1, np.int32)
        match_dist = np.zeros((mm,), np.float32)
        work = dv.copy()
        for _ in range(min(n, mm)):
            r, c = np.unravel_index(np.argmax(work), work.shape)
            if work[r, c] <= 0:
                break
            match_idx[c] = r
            match_dist[c] = dv[r, c]
            work[r, :] = -1.0
            work[:, c] = -1.0
        if match_type == "per_prediction":
            for c in range(mm):
                if match_idx[c] == -1:
                    r = int(np.argmax(dv[:, c]))
                    if dv[r, c] >= dist_threshold:
                        match_idx[c] = r
                        match_dist[c] = dv[r, c]
        outs_i.append(match_idx)
        outs_d.append(match_dist)
    ii = Tensor(jnp.asarray(np.stack(outs_i)), stop_gradient=True)
    dd = Tensor(jnp.asarray(np.stack(outs_d)), stop_gradient=True)
    return ii, dd


def target_assign(input, matched_indices, negative_indices=None,  # noqa: A002
                  mismatch_value=0, name=None):
    """Scatter per-target rows by match indices (reference:
    detection/target_assign_op): out[b, j] = input[b, match[b, j]] when
    match[b, j] >= 0 else mismatch_value; out_weight 1/0 accordingly
    (negative_indices rows also get weight 1)."""
    def raw(xv, mi):
        b, m = mi.shape
        matched = mi >= 0
        safe = jnp.clip(mi, 0)
        rows = jnp.take_along_axis(
            xv, safe[:, :, None].astype(jnp.int32), axis=1)
        out = jnp.where(matched[:, :, None], rows,
                        jnp.asarray(mismatch_value, xv.dtype))
        wgt = matched.astype(jnp.float32)[:, :, None]
        return out, wgt
    out, wgt = raw(unwrap(input), unwrap(matched_indices))
    if negative_indices is not None:
        neg = unwrap(negative_indices)
        wgt = wgt.at[:, :, 0].max(
            jnp.zeros_like(wgt[:, :, 0]).at[
                jnp.arange(neg.shape[0])[:, None],
                jnp.clip(neg, 0)].set(1.0))
    return Tensor(out), Tensor(wgt)


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Re-merge per-level FPN proposals and keep the global top-N by score
    (reference: detection/collect_fpn_proposals_op — the inverse of
    distribute_fpn_proposals).  Host-side, like its reference kernel."""
    rois = np.concatenate([np.asarray(jax.device_get(unwrap(r)))
                           for r in multi_rois], axis=0)
    scores = np.concatenate([np.asarray(jax.device_get(unwrap(s))).reshape(-1)
                             for s in multi_scores], axis=0)
    order = np.argsort(-scores, kind="stable")[:post_nms_top_n]
    if rois_num_per_level is not None:
        # track each roi's image index through the global sort so the output
        # carries one count PER IMAGE (the reference op's contract), then
        # regroup the kept rois by image like collect_fpn_proposals_op does
        counts = [np.asarray(jax.device_get(unwrap(c))).astype(np.int64)
                  for c in rois_num_per_level]
        n_imgs = len(counts[0])
        img_idx = np.concatenate([np.repeat(np.arange(n_imgs), c)
                                  for c in counts])
        kept_img = img_idx[order]
        regroup = np.argsort(kept_img, kind="stable")
        out = rois[order][regroup]
        rois_num = np.bincount(kept_img, minlength=n_imgs).astype(np.int32)
        return (Tensor(jnp.asarray(out), stop_gradient=True),
                Tensor(jnp.asarray(rois_num), stop_gradient=True))
    return Tensor(jnp.asarray(rois[order]), stop_gradient=True)


# ---------------------------------------------------------------------------
# SSD head / loss family (reference: fluid/layers/detection.py:621,1513,1925
# — detection_output, ssd_loss, density_prior_box over the
# detection/{prior_box,bipartite_match,target_assign,mine_hard_examples,
# multiclass_nms}_op kernels)


def density_prior_box(input, image, densities=None, fixed_sizes=None,  # noqa: A002
                      fixed_ratios=None, variance=(0.1, 0.1, 0.2, 0.2),
                      clip=False, steps=(0.0, 0.0), offset=0.5,
                      flatten_to_2d=False, name=None):
    """Density prior boxes (reference: detection/density_prior_box_op.h:80):
    per cell, each (fixed_size_i, density_i) pair drops a density_i x
    density_i grid of shifted centers for every fixed_ratio.  Returns
    (boxes (H, W, P, 4) normalized + clamped to [0,1], variances) — or
    (H*W*P, 4) with flatten_to_2d."""
    if not densities or not fixed_sizes:
        raise ValueError("density_prior_box: densities and fixed_sizes "
                         "are required")
    if len(densities) != len(fixed_sizes):
        raise ValueError("densities and fixed_sizes must align")
    fixed_ratios = list(fixed_ratios or [1.0])
    ih, iw = unwrap(input).shape[-2:]
    imh, imw = unwrap(image).shape[-2:]
    step_w = steps[0] or float(imw) / iw
    step_h = steps[1] or float(imh) / ih
    step_average = int((step_w + step_h) * 0.5)  # kernel truncates to int

    whs, offs = [], []  # per-prior (w, h) and center offsets (dx, dy)
    for size, density in zip(fixed_sizes, densities):
        density = int(density)
        shift = int(step_average / density)
        for r in fixed_ratios:
            bw = float(size) * math.sqrt(r)
            bh = float(size) / math.sqrt(r)
            base = -step_average / 2.0 + shift / 2.0
            for di in range(density):
                for dj in range(density):
                    whs.append((bw, bh))
                    offs.append((base + dj * shift, base + di * shift))
    whs_a = jnp.asarray(whs, jnp.float32)      # (P, 2)
    offs_a = jnp.asarray(offs, jnp.float32)    # (P, 2)

    cx = (jnp.arange(iw, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(ih, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)            # (H, W)
    pcx = cxg[:, :, None] + offs_a[:, 0]       # (H, W, P)
    pcy = cyg[:, :, None] + offs_a[:, 1]
    half_w = whs_a[:, 0] / 2.0
    half_h = whs_a[:, 1] / 2.0
    # the kernel clamps each coordinate while writing (clip re-clips)
    boxes = jnp.stack(
        [jnp.maximum((pcx - half_w) / imw, 0.0),
         jnp.maximum((pcy - half_h) / imh, 0.0),
         jnp.minimum((pcx + half_w) / imw, 1.0),
         jnp.minimum((pcy + half_h) / imh, 1.0)], axis=-1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32), boxes.shape)
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(boxes, stop_gradient=True), Tensor(var, stop_gradient=True)


def _match_batched(iou, match_type, overlap_threshold):
    """Jittable greedy bipartite matching, vmapped over the batch — the
    in-graph twin of `bipartite_match` (whose host loop mirrors the
    reference's CPU-only kernel).  iou (B, M, Np) with padded gt rows
    all-zero; returns (match_idx (B, Np) int32 gt row or -1, match_dist)."""
    from jax import lax

    def one(dv):
        m, npr = dv.shape

        def body(_, carry):
            work, midx, mdist = carry
            flat = jnp.argmax(work)
            r, c = flat // npr, flat % npr
            ok = work[r, c] > 0
            midx = jnp.where(ok, midx.at[c].set(r.astype(jnp.int32)), midx)
            mdist = jnp.where(ok, mdist.at[c].set(dv[r, c]), mdist)
            work = jnp.where(ok,
                             work.at[r, :].set(-1.0).at[:, c].set(-1.0),
                             work)
            return work, midx, mdist

        carry = (dv.astype(jnp.float32),
                 jnp.full((npr,), -1, jnp.int32),
                 jnp.zeros((npr,), jnp.float32))
        _, midx, mdist = lax.fori_loop(0, m, body, carry)
        if match_type == "per_prediction":
            r = jnp.argmax(dv, axis=0).astype(jnp.int32)
            d = jnp.max(dv, axis=0).astype(jnp.float32)
            extra = (midx < 0) & (d >= overlap_threshold)
            midx = jnp.where(extra, r, midx)
            mdist = jnp.where(extra, d, mdist)
        return midx, mdist

    return jax.vmap(one)(iou)


def _pad_gt(gt_box, gt_label, gt_count):
    """Normalize ground truth to dense padded form (B, M, 4)/(B, M)/(B,)
    — the repo's LoD answer (SURVEY: masked-dense sequence toolkit)."""
    if isinstance(gt_box, (list, tuple)):
        boxes = [np.asarray(jax.device_get(unwrap(b))).reshape(-1, 4)
                 for b in gt_box]
        labels = [np.asarray(jax.device_get(unwrap(l))).reshape(-1)
                  for l in gt_label]
        m = max(1, max(len(b) for b in boxes))
        gb = np.zeros((len(boxes), m, 4), np.float32)
        gl = np.zeros((len(boxes), m), np.int32)
        cnt = np.zeros((len(boxes),), np.int32)
        for i, (b, l) in enumerate(zip(boxes, labels)):
            gb[i, :len(b)] = b
            gl[i, :len(l)] = l
            cnt[i] = len(b)
        return jnp.asarray(gb), jnp.asarray(gl), jnp.asarray(cnt)
    gb = unwrap(gt_box)
    gl = unwrap(gt_label)
    if gl.ndim == 3:
        gl = gl[..., 0]
    if gt_count is None:
        cnt = jnp.full((gb.shape[0],), gb.shape[1], jnp.int32)
    else:
        cnt = unwrap(gt_count).astype(jnp.int32)
    return gb, gl.astype(jnp.int32), cnt


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,  # noqa: A002
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             gt_count=None, name=None):
    """SSD multi-box loss (reference: fluid/layers/detection.py:1513 over
    bipartite_match + target_assign + mine_hard_examples kernels).

    TPU-native: ONE jittable dense computation — matching runs in-graph
    (`_match_batched` lax loop), loc targets are gathered per prior then
    encoded elementwise (the reference materializes an (M, Np, 4) encode
    and scatters it), and max_negative mining is a rank-vs-quota mask
    instead of per-image sorted index lists.  Ground truth is padded dense
    (`gt_box` (B, M, 4) + `gt_count`, or a per-image list — the LoD
    analogue).  Returns (B, 1) per-image weighted loss like the reference
    (its (N*Np, 1) rows summed over priors).
    """
    if mining_type != "max_negative":
        raise ValueError("ssd_loss: only mining_type='max_negative' "
                         "is supported (the reference's hard_example path "
                         "was never finished either)")
    gb, gl, cnt = _pad_gt(gt_box, gt_label, gt_count)
    pb = unwrap(prior_box).reshape(-1, 4)
    pbv = (unwrap(prior_box_var).reshape(-1, 4)
           if prior_box_var is not None else None)

    def raw(loc, conf, gb, gl, cnt):
        b, n_prior, n_cls = conf.shape
        m = gb.shape[1]
        valid = jnp.arange(m)[None, :] < cnt[:, None]          # (B, M)
        iou = jax.vmap(lambda g: _iou_matrix(g, pb))(gb)       # (B, M, Np)
        iou = jnp.where(valid[:, :, None], iou, 0.0)
        midx, mdist = _match_batched(iou, match_type, overlap_threshold)
        matched = midx >= 0                                    # (B, Np)
        safe = jnp.clip(midx, 0)

        # --- confidence loss vs assigned labels (background if unmatched)
        tgt_label = jnp.where(
            matched, jnp.take_along_axis(gl, safe, axis=1), background_label)
        logits = conf.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, tgt_label[..., None].astype(jnp.int32), axis=-1)[..., 0]
        ce = lse - picked                                      # (B, Np)

        # --- max_negative mining: rank eligible priors by conf loss
        eligible = (~matched) & (mdist < neg_overlap)
        rank_key = jnp.where(eligible, jax.lax.stop_gradient(ce), -jnp.inf)
        order = jnp.argsort(-rank_key, axis=1)
        rank = jnp.argsort(order, axis=1)                      # desc rank
        num_pos = jnp.sum(matched, axis=1)
        quota = jnp.minimum((num_pos * neg_pos_ratio).astype(jnp.int32),
                            jnp.sum(eligible, axis=1))
        if sample_size is not None:
            quota = jnp.minimum(quota, sample_size)
        negs = eligible & (rank < quota[:, None])

        conf_w = (matched | negs).astype(jnp.float32)
        conf_loss = ce * conf_w

        # --- localization targets: gather matched gt box per prior, then
        # encode against the prior elementwise
        gtm = jnp.take_along_axis(gb, safe[..., None], axis=1)  # (B, Np, 4)
        pw = pb[:, 2] - pb[:, 0]
        ph = pb[:, 3] - pb[:, 1]
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        tw = gtm[..., 2] - gtm[..., 0]
        th = gtm[..., 3] - gtm[..., 1]
        tcx = gtm[..., 0] + tw / 2
        tcy = gtm[..., 1] + th / 2
        eps = 1e-10
        deltas = jnp.stack(
            [(tcx - pcx) / pw, (tcy - pcy) / ph,
             jnp.log(jnp.maximum(tw, eps) / pw),
             jnp.log(jnp.maximum(th, eps) / ph)], axis=-1)
        if pbv is not None:
            deltas = deltas / pbv
        target_bbox = jnp.where(matched[..., None], deltas, 0.0)
        loc_w = matched.astype(jnp.float32)

        diff = jnp.abs(loc.astype(jnp.float32) - target_bbox)
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
        loc_loss = jnp.sum(sl1, axis=-1) * loc_w               # (B, Np)

        per_prior = conf_loss_weight * conf_loss + loc_loss_weight * loc_loss
        per_image = jnp.sum(per_prior, axis=1, keepdims=True)  # (B, 1)
        if normalize:
            per_image = per_image / jnp.maximum(jnp.sum(loc_w), 1.0)
        return per_image

    return dispatch("ssd_loss", raw, location, confidence,
                    Tensor(gb), Tensor(gl), Tensor(cnt))


def detection_output(loc, scores, prior_box, prior_box_var,  # noqa: A002
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False, name=None):
    """SSD serving head (reference: fluid/layers/detection.py:621 over
    box_coder + multiclass_nms kernels): decode loc deltas against the
    priors, then per-image multiclass NMS.

    TPU-native contract: FIXED output extents instead of LoD — returns
    (out (B, keep_top_k, 6) rows [label, score, x1, y1, x2, y2] padded
    with -1, valid counts (B,)), plus flat prior indices (B, keep_top_k)
    when return_index.  Scores are raw confidences — softmax is applied
    internally like the reference (detection.py:721), and the batch NMS is
    a single `jax.vmap` program (the reference multiclass_nms op is
    batched), so the whole path jits for serving with a B-independent
    trace."""
    lv = unwrap(loc)
    sv = unwrap(scores)
    pb = unwrap(prior_box).reshape(-1, 4)
    pbv = (unwrap(prior_box_var).reshape(-1, 4)
           if prior_box_var is not None else None)

    decoded = unwrap(box_coder(
        Tensor(pb), Tensor(pbv) if pbv is not None else None, Tensor(lv),
        code_type="decode_center_size", axis=1))                # (B, Np, 4)

    def raw(decoded, sv):
        probs = jax.nn.softmax(sv.astype(jnp.float32), axis=-1)
        probs_t = jnp.swapaxes(probs, 1, 2)                    # (B, C, Np)
        rows, cnts = jax.vmap(
            lambda d, s: _nms_padded_raw(
                d, s, score_threshold, nms_top_k, keep_top_k,
                nms_threshold, background_label))(decoded, probs_t)
        return rows, cnts

    rows, cnts = raw(decoded, sv)
    out = Tensor(rows, stop_gradient=True)
    cnts = Tensor(cnts, stop_gradient=True)
    if return_index:
        # index = argmax over priors of IoU with the kept box (exact match)
        def row_index(dec, rows):
            ious = jax.vmap(
                lambda r: _iou_matrix(r[None, 2:6], dec)[0])(rows)
            return jnp.where(rows[:, 0] >= 0,
                             jnp.argmax(ious, axis=1), -1).astype(jnp.int32)
        flat = jax.vmap(row_index)(decoded, unwrap(out))
        return out, cnts, Tensor(flat, stop_gradient=True)
    return out, cnts


# ---------------------------------------------------------------------------
# R-FCN / precise-RoI pooling lineage (reference: psroi_pool_op.h:24,
# prroi_pool_op.h, deformable_psroi_pooling_op.h:59 — surfaced via
# fluid/layers/nn.py psroi_pool/prroi_pool/deformable_roi_pooling)


def _roi_batch_ids(boxes_num, n_rois):
    if boxes_num is None:
        return jnp.zeros((n_rois,), jnp.int32)
    bn = unwrap(boxes_num).astype(jnp.int32)
    return jnp.repeat(jnp.arange(bn.shape[0], dtype=jnp.int32), bn,
                      total_repeat_length=n_rois)


def psroi_pool(x, boxes, output_channels, spatial_scale=1.0,
               pooled_height=1, pooled_width=1, boxes_num=None, name=None):
    """Position-sensitive RoI average pooling (reference psroi_pool_op.h:24):
    bin (ph, pw) of output channel c averages input channel
    (c*PH + ph)*PW + pw over the bin's integer pixel extent.  TPU-native:
    the variable integer bin extents become per-bin row/col masks and one
    einsum per roi (vmapped) — no scalar loops."""
    xv = unwrap(x)
    rv = unwrap(boxes)
    n, c_in, hgt, wid = xv.shape
    ph_n, pw_n = int(pooled_height), int(pooled_width)
    if c_in != output_channels * ph_n * pw_n:
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"psroi_pool: input channels {c_in} != output_channels * "
            f"pooled_height * pooled_width = "
            f"{output_channels * ph_n * pw_n}")
    ids = _roi_batch_ids(boxes_num, rv.shape[0])

    def raw(xv, rv):
        xr = xv.reshape(n, output_channels, ph_n, pw_n, hgt, wid)

        def one(roi, bid):
            sw = jnp.round(roi[0]) * spatial_scale
            sh = jnp.round(roi[1]) * spatial_scale
            ew = (jnp.round(roi[2]) + 1.0) * spatial_scale
            eh = (jnp.round(roi[3]) + 1.0) * spatial_scale
            rh = jnp.maximum(eh - sh, 0.1)
            rw = jnp.maximum(ew - sw, 0.1)
            bh = rh / ph_n
            bw = rw / pw_n
            pi = jnp.arange(ph_n, dtype=jnp.float32)
            pj = jnp.arange(pw_n, dtype=jnp.float32)
            hs = jnp.clip(jnp.floor(pi * bh + sh), 0, hgt).astype(jnp.int32)
            he = jnp.clip(jnp.ceil((pi + 1) * bh + sh), 0, hgt).astype(
                jnp.int32)
            ws = jnp.clip(jnp.floor(pj * bw + sw), 0, wid).astype(jnp.int32)
            we = jnp.clip(jnp.ceil((pj + 1) * bw + sw), 0, wid).astype(
                jnp.int32)
            mh = ((jnp.arange(hgt)[None, :] >= hs[:, None])
                  & (jnp.arange(hgt)[None, :] < he[:, None])).astype(
                      xv.dtype)                                  # (PH, H)
            mw = ((jnp.arange(wid)[None, :] >= ws[:, None])
                  & (jnp.arange(wid)[None, :] < we[:, None])).astype(
                      xv.dtype)                                  # (PW, W)
            s = jnp.einsum("ph,qw,cpqhw->cpq", mh, mw, xr[bid])
            area = ((he - hs)[:, None] * (we - ws)[None, :]).astype(xv.dtype)
            return jnp.where(area > 0, s / jnp.maximum(area, 1.0), 0.0)

        return jax.vmap(one)(rv, ids)

    return dispatch("psroi_pool", raw, x, boxes)


def _tent_integrals(lo, hi, size):
    """wx[i] = integral over [lo, hi] of the unit tent centered at pixel i
    (zero outside the array: PrRoI treats out-of-range samples as 0).
    Closed form via the tent antiderivative; vectorized over i."""
    i = jnp.arange(size, dtype=jnp.float32)

    def anti(t):
        # antiderivative of max(0, 1-|t|) from -inf, in the tent's frame
        t = jnp.clip(t, -1.0, 1.0)
        return jnp.where(t <= 0.0,
                         0.5 * (t + 1.0) ** 2,
                         1.0 - 0.5 * (1.0 - t) ** 2)

    return anti(hi[..., None] - i) - anti(lo[..., None] - i)


def prroi_pool(x, boxes, pooled_height=1, pooled_width=1, spatial_scale=1.0,
               boxes_num=None, batch_roi_nums=None, name=None):
    """Precise RoI pooling (reference prroi_pool_op: Jiang et al. 2018):
    each bin is the EXACT integral of the bilinearly-interpolated feature
    over the bin rectangle, divided by its area — continuously
    differentiable in the roi coords (no rounding, no sampling).

    TPU-native closed form: bilinear interpolation is a tensor product of
    tent bases, so the 2-D integral separates into per-axis tent-integral
    weight vectors and one einsum per roi."""
    if batch_roi_nums is not None and boxes_num is None:
        boxes_num = batch_roi_nums
    xv = unwrap(x)
    rv = unwrap(boxes)
    _, _, hgt, wid = xv.shape
    ph_n, pw_n = int(pooled_height), int(pooled_width)
    ids = _roi_batch_ids(boxes_num, rv.shape[0])

    def raw(xv, rv):
        def one(roi, bid):
            sw, sh = roi[0] * spatial_scale, roi[1] * spatial_scale
            ew, eh = roi[2] * spatial_scale, roi[3] * spatial_scale
            pi = jnp.arange(ph_n, dtype=jnp.float32)
            pj = jnp.arange(pw_n, dtype=jnp.float32)
            bh = (eh - sh) / ph_n
            bw = (ew - sw) / pw_n
            h1 = sh + pi * bh
            h2 = sh + (pi + 1) * bh
            w1 = sw + pj * bw
            w2 = sw + (pj + 1) * bw
            wy = _tent_integrals(h1, h2, hgt)        # (PH, H)
            wx = _tent_integrals(w1, w2, wid)        # (PW, W)
            s = jnp.einsum("ph,qw,chw->cpq", wy, wx, xv[bid])
            area = (jnp.maximum(h2 - h1, 0.0)[:, None]
                    * jnp.maximum(w2 - w1, 0.0)[None, :])
            return jnp.where(area > 0, s / jnp.maximum(area, 1e-10), 0.0)

        return jax.vmap(one)(rv, ids)

    return dispatch("prroi_pool", raw, x, boxes)


def deformable_roi_pooling(input, rois, trans, no_trans=False,  # noqa: A002
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, boxes_num=None,
                           name=None):
    """Deformable (PS-)RoI pooling (reference
    deformable_psroi_pooling_op.h:59, Dai et al. 2017): each bin is shifted
    by a learned normalized offset from `trans`, then averaged over
    sample_per_part^2 bilinear samples; samples outside the feature map
    are dropped from the average.  Fully vectorized (vmap over rois,
    dense sample grid) and differentiable through both input and trans."""
    xv = unwrap(input)
    rv = unwrap(rois)
    tv = unwrap(trans) if trans is not None else None
    n, c_in, hgt, wid = xv.shape
    ph_n, pw_n = int(pooled_height), int(pooled_width)
    gh_n, gw_n = int(group_size[0]), int(group_size[1])
    out_dim = c_in // (ph_n * pw_n) if position_sensitive else c_in
    if part_size is None:
        part_h, part_w = ph_n, pw_n
    elif isinstance(part_size, int):
        part_h = part_w = int(part_size)
    else:
        part_h, part_w = int(part_size[0]), int(part_size[1])
    spp = int(sample_per_part)
    ids = _roi_batch_ids(boxes_num, rv.shape[0])
    num_classes = 1 if (no_trans or tv is None) else tv.shape[1] // 2
    ch_each = max(out_dim // num_classes, 1)

    def raw(xv, rv, tv):
        def one(roi, bid, tr):
            sw = jnp.round(roi[0]) * spatial_scale - 0.5
            sh = jnp.round(roi[1]) * spatial_scale - 0.5
            ew = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
            eh = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
            rw = jnp.maximum(ew - sw, 0.1)
            rh = jnp.maximum(eh - sh, 0.1)
            bh = rh / ph_n
            bw = rw / pw_n
            pi = jnp.arange(ph_n)
            pj = jnp.arange(pw_n)
            cc = jnp.arange(out_dim)
            # per-bin part cell and learned offset
            p_h = jnp.floor(pi.astype(jnp.float32) / ph_n * part_h).astype(
                jnp.int32)
            p_w = jnp.floor(pj.astype(jnp.float32) / pw_n * part_w).astype(
                jnp.int32)
            cls = cc // ch_each                                  # (C,)
            if no_trans or tv is None:
                tx = jnp.zeros((out_dim, ph_n, pw_n))
                ty = jnp.zeros((out_dim, ph_n, pw_n))
            else:
                tx = tr[cls[:, None, None] * 2,
                        p_h[None, :, None], p_w[None, None, :]] * trans_std
                ty = tr[cls[:, None, None] * 2 + 1,
                        p_h[None, :, None], p_w[None, None, :]] * trans_std
            wstart = (pj.astype(jnp.float32) * bw + sw)[None, None, :] \
                + tx * rw
            hstart = (pi.astype(jnp.float32) * bh + sh)[None, :, None] \
                + ty * rh
            # dense sample grid (C, PH, PW, S, S)
            si = jnp.arange(spp, dtype=jnp.float32)
            wpos = wstart[..., None, None] + si[None, :] * (bw / spp)
            hpos = hstart[..., None, None] + si[:, None] * (bh / spp)
            ok = ((wpos >= -0.5) & (wpos <= wid - 0.5)
                  & (hpos >= -0.5) & (hpos <= hgt - 0.5))
            wc = jnp.clip(wpos, 0.0, wid - 1.0)
            hc = jnp.clip(hpos, 0.0, hgt - 1.0)
            if position_sensitive:
                # position-sensitive channel: (c*GH + gh)*GW + gw
                gh = jnp.clip((pi * gh_n) // ph_n, 0, gh_n - 1)
                gw = jnp.clip((pj * gw_n) // pw_n, 0, gw_n - 1)
                chan = ((cc[:, None, None] * gh_n + gh[None, :, None])
                        * gw_n + gw[None, None, :])              # (C, PH, PW)
            else:
                chan = jnp.broadcast_to(cc[:, None, None],
                                        (out_dim, ph_n, pw_n))
            feat = xv[bid]                                       # (C_in, H, W)
            h0 = jnp.floor(hc).astype(jnp.int32)
            w0 = jnp.floor(wc).astype(jnp.int32)
            h1 = jnp.minimum(h0 + 1, hgt - 1)
            w1 = jnp.minimum(w0 + 1, wid - 1)
            lh = hc - h0
            lw = wc - w0
            cb = jnp.broadcast_to(chan[..., None, None], h0.shape)
            v = (feat[cb, h0, w0] * (1 - lh) * (1 - lw)
                 + feat[cb, h0, w1] * (1 - lh) * lw
                 + feat[cb, h1, w0] * lh * (1 - lw)
                 + feat[cb, h1, w1] * lh * lw)
            v = jnp.where(ok, v, 0.0)
            cnt = jnp.sum(ok.astype(xv.dtype), axis=(-1, -2))
            return jnp.where(cnt > 0,
                             jnp.sum(v, axis=(-1, -2))
                             / jnp.maximum(cnt, 1.0), 0.0)

        tv_use = (jnp.zeros((rv.shape[0], 2, part_h, part_w), xv.dtype)
                  if (no_trans or tv is None) else tv)
        return jax.vmap(one)(rv, ids, tv_use)

    return dispatch("deformable_roi_pooling", raw, input, rois,
                    trans if tv is not None else Tensor(
                        jnp.zeros((rv.shape[0], 2, part_h, part_w),
                                  xv.dtype)))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference: fluid/layers/detection.py:3544 over
    detection/matrix_nms_op.cc — SOLOv2, arXiv:2003.10152): instead of the
    sequential greedy loop, every candidate's score decays by the worst
    pairwise decay against all HIGHER-scored candidates of its class:
      gaussian: exp((iou_max_j^2 - iou_ij^2) * sigma)
      linear:   (1 - iou_ij) / (1 - iou_max_j)
    This is embarrassingly parallel — the ideal TPU suppression op; the
    whole computation is one jittable dense expression per image.

    bboxes (N, M, 4), scores (N, C, M).  Returns (out (N, keep_top_k, 6)
    rows [label, score, x1, y1, x2, y2] padded with -1, rois_num (N,)
    [, index (N, keep_top_k) flat class*M+box indices])."""
    bv = unwrap(bboxes)
    sv = unwrap(scores)
    n, c, m = sv.shape
    topn = min(nms_top_k, m) if nms_top_k and nms_top_k > 0 else m
    norm = 0.0 if normalized else 1.0

    def one_image(bx, sc):
        iou = _iou_matrix(bx, bx, norm)                   # (M, M)

        def one_class(srow):
            valid = srow >= score_threshold
            key = jnp.where(valid, srow, -jnp.inf)
            order = jnp.argsort(-key)[:topn]              # (k,)
            s_sorted = key[order]
            ok = jnp.isfinite(s_sorted)
            iou_s = iou[order][:, order]                  # (k, k)
            k = iou_s.shape[0]
            upper = jnp.arange(k)[:, None] < jnp.arange(k)[None, :]
            iou_u = jnp.where(upper, iou_s, 0.0)          # j<i at [j, i]
            iou_max = jnp.max(iou_u, axis=0)              # per i over j<i
            iou_max_j = jnp.max(jnp.where(
                jnp.arange(k)[:, None] > jnp.arange(k)[None, :],
                iou_s, 0.0), axis=1)                      # per row j
            if use_gaussian:
                decay = jnp.exp((iou_max_j[:, None] ** 2 - iou_u ** 2)
                                * gaussian_sigma)
            else:
                decay = (1.0 - iou_u) / jnp.maximum(
                    1.0 - iou_max_j[:, None], 1e-10)
            decay = jnp.where(upper, decay, 1.0)
            dec = jnp.min(decay, axis=0)
            ds = jnp.where(ok, s_sorted * dec, -jnp.inf)
            if post_threshold > 0.0:
                ds = jnp.where(ds >= post_threshold, ds, -jnp.inf)
            return ds, order

        ds, order = jax.vmap(one_class)(sc)               # (C, k)
        if 0 <= background_label < c:
            ds = ds.at[background_label].set(-jnp.inf)
        flat = ds.reshape(-1)
        kk = min(keep_top_k, flat.shape[0])
        top_s, top_i = jax.lax.top_k(flat, kk)
        cls = (top_i // ds.shape[1]).astype(jnp.float32)
        box_i = jnp.take_along_axis(
            order.reshape(-1), top_i, axis=0)
        valid = jnp.isfinite(top_s)
        rows = jnp.concatenate(
            [cls[:, None], jnp.where(valid, top_s, -1.0)[:, None],
             bx[box_i]], axis=1)
        rows = jnp.where(valid[:, None], rows, -1.0)
        if kk < keep_top_k:
            rows = jnp.concatenate(
                [rows, jnp.full((keep_top_k - kk, 6), -1.0)], axis=0)
        flat_idx = jnp.where(valid,
                             (top_i // ds.shape[1]) * m + box_i, -1)
        if kk < keep_top_k:
            flat_idx = jnp.concatenate(
                [flat_idx, jnp.full((keep_top_k - kk,), -1, jnp.int32)])
        return rows, jnp.sum(valid.astype(jnp.int32)), flat_idx

    rows, counts, idxs = jax.vmap(one_image)(bv, sv)
    out = (Tensor(rows, stop_gradient=True),)
    if return_rois_num:
        out += (Tensor(counts, stop_gradient=True),)
    if return_index:
        out += (Tensor(idxs.astype(jnp.int32), stop_gradient=True),)
    return out if len(out) > 1 else out[0]
