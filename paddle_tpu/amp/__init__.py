"""Automatic mixed precision.

Reference: dygraph AMP — imperative/amp_auto_cast.cc (AmpOperators white/black
lists + AutoCastGuard) and python/paddle/amp/{auto_cast.py, grad_scaler.py};
static AMP — fluid/contrib/mixed_precision/{decorator,fp16_lists,fp16_utils}.
TPU-native: the policy is a dtype-cast hook on op dispatch (eager and traced
alike), bf16 is the native fast dtype so GradScaler's loss-scaling state
machine (operators/amp/update_loss_scaling_op) is only exercised for fp16.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import op as _op
from ..core.tensor import Tensor

# ops always run in the low-precision dtype (MXU-bound) —
# reference fp16 white list: fp16_lists.py white_list
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "addmm", "scaled_dot_product_attention",
}
# ops that must stay fp32 (numerically sensitive) — reference black_list
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "softmax", "log_softmax",
    "cross_entropy", "nll_loss", "binary_cross_entropy", "bce_with_logits",
    "kl_div", "softmax_with_cross_entropy", "mean", "sum", "norm", "var",
    "std", "layer_norm", "batch_norm", "instance_norm", "group_norm",
    "logsumexp", "erf", "erfinv", "rsqrt", "pow", "square", "ctc_loss",
    "cumsum", "cosine_similarity",
}


class _AmpState:
    enabled = False
    dtype = jnp.bfloat16
    level = "O1"
    white = frozenset()
    black = frozenset()


_state = _AmpState()


def _amp_hook(name, raw_leaves, tensor_idx):
    if not _state.enabled:
        return raw_leaves
    in_white = name in _state.white
    in_black = name in _state.black
    if _state.level == "O2":
        cast_low = not in_black
    else:
        cast_low = in_white
    if cast_low:
        tgt = _state.dtype
    elif in_black:
        tgt = jnp.float32
    else:
        return raw_leaves
    out = list(raw_leaves)
    for i in tensor_idx:
        x = out[i]
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating) \
                and x.dtype != jnp.dtype(tgt):
            out[i] = x.astype(tgt)
    return out


def _amp_cache_key():
    """Hashable snapshot of the autocast policy for the dispatch fast-path
    cache: any state change (enable, dtype, level, custom lists) must miss."""
    if not _state.enabled:
        return None
    return (_state.level, jnp.dtype(_state.dtype).name, _state.white,
            _state.black)


_op.set_amp_hook(_amp_hook, _amp_cache_key)


class auto_cast:
    """paddle.amp.auto_cast context manager."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = jnp.float16 if str(dtype) in ("float16", "fp16") else jnp.bfloat16
        self.white = set(WHITE_LIST) | set(custom_white_list or ())
        self.black = (set(BLACK_LIST) | set(custom_black_list or ())) - set(custom_white_list or ())

    def __enter__(self):
        self._prev = (_state.enabled, _state.dtype, _state.level,
                      _state.white, _state.black)
        _state.enabled = self.enable
        _state.dtype = self.dtype
        _state.level = self.level
        _state.white = frozenset(self.white)
        _state.black = frozenset(self.black)
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.dtype, _state.level,
         _state.white, _state.black) = self._prev
        return False


amp_guard = auto_cast


def is_amp_enabled():
    return _state.enabled


def decorate(models=None, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate — O2 casts model params to the low dtype.
    Master weights are implicit: optimizer states are kept fp32 (see
    Optimizer.init_state) which is the multi_precision behavior."""
    tgt = "float16" if str(dtype) in ("float16", "fp16") else "bfloat16"
    def _cast(m):
        if m is not None and level == "O2":
            m.to(dtype=tgt)
        return m
    if isinstance(models, (list, tuple)):
        models = [_cast(m) for m in models]
    else:
        models = _cast(models)
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Dynamic loss scaling (reference: python/paddle/amp/grad_scaler.py +
    operators/amp/update_loss_scaling_op.cc state machine)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False  # last unscale_ result (back-compat mirror)
        # per-optimizer cycle state (reference OptimizerState): keyed by id
        # with a weakref identity check so a recycled id from a dropped
        # optimizer can never inherit a stale "already unscaled" guard
        self._opt_states = {}  # id -> dict(ref, unscaled, found_inf)
        self._jit_unscale = None  # cached by jax.jit on leaf count/shapes

    def _opt_state(self, optimizer):
        import weakref
        # purge entries whose optimizer has been garbage-collected
        dead = [k for k, st in self._opt_states.items() if st["ref"]() is None]
        for k in dead:
            del self._opt_states[k]
        st = self._opt_states.get(id(optimizer))
        if st is None or st["ref"]() is not optimizer:
            st = {"ref": weakref.ref(optimizer), "unscaled": False,
                  "found_inf": False}
            self._opt_states[id(optimizer)] = st
        return st

    def scale(self, loss):
        if not self._enable:
            return loss
        # NOTE: does not reset unscale guards — they are per-optimizer
        # (cleared by that optimizer's step()), so a multi-loss interleave
        # (scale(loss_g) between unscale_(opt_d) and step(opt_d)) cannot
        # trigger a double division (reference: OptimizerState per optimizer)
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        st = self._opt_state(optimizer)
        if st["unscaled"]:
            # explicit unscale_ + step workflow (grad clipping): step's
            # internal unscale_ must not divide a second time (the
            # reference guards this per-optimizer via OptimizerState)
            return
        st["unscaled"] = True
        from ..core.selected_rows import RowSparseGrad
        inv = 1.0 / self._scale
        # ONE fused device program + ONE host sync for the whole grad set
        # (the reference keeps the loss-scale state machine on device,
        # update_loss_scaling_op.cc; a per-param bool() would host-sync
        # per tensor)
        dense, sparse = [], []
        for p in optimizer._parameter_list or []:
            if p.grad is None:
                continue
            (sparse if isinstance(p.grad, RowSparseGrad)
             else dense).append(p)
        leaves = [p.grad._data for p in dense] + \
            [p.grad.values for p in sparse]
        if not leaves:
            self._found_inf = st["found_inf"] = False
            return
        if self._jit_unscale is None:
            def _unscale(leaves, inv):
                out = [(g.astype(jnp.float32) * inv).astype(g.dtype)
                       for g in leaves]
                finite = jnp.all(jnp.stack(
                    [jnp.all(jnp.isfinite(g)) for g in out]))
                return out, finite
            self._jit_unscale = jax.jit(_unscale)
        out, finite = self._jit_unscale(leaves, jnp.float32(inv))
        self._found_inf = st["found_inf"] = not bool(finite)  # one host sync
        for p, g in zip(dense, out[:len(dense)]):
            p.grad._set_data(g)
        for p, v in zip(sparse, out[len(dense):]):
            p.grad = RowSparseGrad(p.grad.rows, v, p.grad.dense_shape)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        st = self._opt_state(optimizer)
        # decide from THIS optimizer's unscale result, not whichever
        # optimizer was unscaled last (multi-loss GAN interleave)
        if not st["found_inf"]:
            optimizer.step()
        self._update(st["found_inf"])
        st["unscaled"] = False
        st["found_inf"] = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        optimizer.clear_grad()

    def update(self):
        pass  # folded into step, kept for API compat

    def _update(self, found_inf):
        if not self._dynamic:
            return
        if found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def record_skip(self):
        """Feed an externally-detected bad step (GuardedTrainStep's
        on-device nonfinite verdict) into the dynamic loss-scale state
        machine: counts as a found_inf step, decaying the scale per
        decr_every_n_nan_or_inf — the decay half of skip-and-decay without
        a per-grad host isfinite pass."""
        from ..utils.monitor import stat_add
        stat_add("STAT_amp_skipped_steps")
        self._update(True)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)

    # checkpoint extras use the paddle spelling pair
    set_state_dict = load_state_dict
