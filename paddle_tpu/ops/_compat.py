"""Shared pallas/jax version-compat helpers for the kernel modules."""
from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams around 0.5; support both
CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")
