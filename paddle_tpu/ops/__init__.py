"""paddle_tpu.ops — custom TPU kernels (pallas) and fused ops.

Replaces the reference's hand-written CUDA fused ops
(paddle/fluid/operators/fused/) with pallas/Mosaic kernels.
"""
from . import flash_attention  # noqa: F401
from . import fused_bn_act  # noqa: F401
from . import int8_matmul  # noqa: F401
