"""Int8 weight-only dequant-matmul for the serving decode path (pallas).

The serving decode step is weight-HBM-bound: every token reads every
weight once.  Holding the weights as int8 + per-output-channel fp32
scales halves the bytes per step (vs bf16; 4x vs f32) — the activation
stays floating point, so the MXU still computes in bf16/f32 and accuracy
is bounded by the ~1/127 per-channel weight quantization error alone
(`quantization.quantize_for_serving` builds the int8 buffers).

Two implementations behind one call:

- **pallas kernel** (TPU, or `_INTERPRET` for tests): grid over
  (M-blocks, N-blocks); each program DMAs an int8 weight block into VMEM,
  dequantizes it in-register against its scale slice, and feeds the MXU —
  the weight moves HBM->VMEM in int8, which is the entire point.  Design
  notes: /opt/skills/guides/pallas_guide.md (min int8 tile (32, 128):
  the gate below requires K % 32 == 0 and N % 128 == 0; M is padded to
  the sublane multiple).
- **jnp fallback** (CPU and unaligned shapes):
  ``x @ (w_int8.astype(x.dtype) * scale)`` — XLA fuses the dequant into
  the dot, so the fallback is one fused program too (the form the
  quantization package already relies on).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["dequant_matmul"]

_INTERPRET = False  # tests flip this to run the kernel via the interpreter


def _available(m, k, n) -> bool:
    if _INTERPRET:
        return k % 32 == 0 and n % 128 == 0
    try:
        if jax.default_backend() not in ("tpu", "axon"):
            return False
    except Exception:
        return False
    # int8 VMEM tiling: sublane multiple 32 on the contraction axis, lane
    # multiple 128 on the output axis; other shapes take the XLA fallback
    return k % 32 == 0 and n % 128 == 0


def _kernel(x_ref, w_ref, s_ref, o_ref):
    # dequantize the int8 weight block in VMEM and feed the MXU; the f32
    # accumulate keeps the quantization error the only error source
    w = w_ref[...].astype(jnp.float32) * s_ref[0][None, :]
    o_ref[...] = jax.lax.dot(
        x_ref[...].astype(jnp.float32), w,
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _pallas_matmul(x2, w_int8, scale_row):
    m, k = x2.shape
    n = w_int8.shape[1]
    blk_m = m if m <= 256 else 256        # caller pads m to blk_m multiple
    for blk_n in (512, 256, 128):
        if n % blk_n == 0:
            break
    n_m, n_n = m // blk_m, n // blk_n
    return pl.pallas_call(
        _kernel,
        grid=(n_m, n_n),
        in_specs=[
            pl.BlockSpec((blk_m, k), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, blk_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, blk_n), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((blk_m, blk_n), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), x2.dtype),
        interpret=_INTERPRET,
    )(x2, w_int8, scale_row)


def dequant_matmul(x, w_int8, scale):
    """``x (..., K) @ dequant(w_int8 (K, N))`` with ``scale`` the
    per-output-channel multiplier of shape (1, N) (a (1, 1) per-tensor
    scale is broadcast).  Returns (..., N) in x's dtype.  Raw jax arrays
    in and out — Layer wrappers live in `paddle_tpu.quantization`."""
    k, n = w_int8.shape
    scale_row = jnp.broadcast_to(scale.astype(jnp.float32), (1, n))
    lead = x.shape[:-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    if _available(m, k, n):
        # pad rows to the block multiple (sublane-aligned); the padded
        # rows are zeros and sliced back off
        blk_m = 256 if m > 256 else max(8, -(-m // 8) * 8)
        pad = (-m) % blk_m
        if pad:
            x2 = jnp.concatenate(
                [x2, jnp.zeros((pad, k), x2.dtype)], axis=0)
        out = _pallas_matmul(x2, w_int8, scale_row)[:m]
    else:
        out = jnp.dot(x2, w_int8.astype(x2.dtype)
                      * scale_row.astype(x2.dtype))
    return out.reshape(lead + (n,))
