"""Fused tied-head softmax cross-entropy.

Reference context: the GPT pretraining head is logits = h @ W_e^T followed
by softmax-CE over a ~50k vocab (models/gpt.py).  Materializing the
(tokens, vocab) logits between forward and backward costs ~0.4-0.8 GB of
HBM traffic per step at GPT-2-medium shapes — the r3 verdict's named lever
("shard or chunk the vocab axis so the CE never materializes (B*S, V) in
f32").

TPU-native: a custom-vjp op that scans TOKEN chunks; each chunk's logits
live only inside the scan step (bf16 MXU dot, f32 accumulation/softmax
math) and the backward recomputes them from the saved (h, W) instead of
stashing (T, V) activations.  dW accumulates in f32 across chunks.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..core.op import dispatch
from ..core.tensor import unwrap

__all__ = ["fused_linear_cross_entropy", "fused_pool_linear_cross_entropy"]


def _chunk_of(t: int, want: int) -> int:
    want = min(want, t)
    while t % want:
        want -= 1
    return want


def _chunk_losses(hc, w, b, lc, valid):
    """One chunk: (c, H) x (V, H) [+ bias] -> per-token CE, logits never
    escape.  `valid` zeroes ignored (e.g. unmasked-MLM) positions."""
    logits = jax.lax.dot_general(
        hc.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)          # (c, V) f32
    if b is not None:
        logits = logits + b.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, lc[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return jnp.where(valid, lse - picked, 0.0)


@partial(jax.custom_vjp, nondiff_argnums=(5,))
def _flce(h, w, b, labels, valid, chunk):
    losses, _ = _flce_fwd(h, w, b, labels, valid, chunk)
    return losses


def _flce_fwd(h, w, b, labels, valid, chunk):
    t, hid = h.shape
    c = _chunk_of(t, chunk)
    hs = h.reshape(t // c, c, hid)
    ls = labels.reshape(t // c, c)
    vs = valid.reshape(t // c, c)
    _, losses = jax.lax.scan(
        lambda _, xs: (None, _chunk_losses(xs[0], w, b, xs[1], xs[2])),
        None, (hs, ls, vs))
    return losses.reshape(t), (h, w, b, labels, valid)


def _flce_bwd(chunk, res, ct):
    h, w, b, labels, valid = res
    t, hid = h.shape
    c = _chunk_of(t, chunk)
    n = t // c
    with_bias = b is not None

    def body(carry, xs):
        dw, db = carry
        hc, lc, vc, ctc = xs
        logits = jax.lax.dot_general(
            hc.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if with_bias:
            logits = logits + b.astype(jnp.float32)
        p = jax.nn.softmax(logits, axis=-1)
        g = p.at[jnp.arange(c), lc.astype(jnp.int32)].add(-1.0)
        g = g * (ctc * vc)[:, None]                   # (c, V) f32
        gb = g.astype(jnp.bfloat16)
        dh_c = jax.lax.dot_general(
            gb, w.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (c, H)
        dw = dw + jax.lax.dot_general(
            gb, hc.astype(jnp.bfloat16), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (V, H)
        if with_bias:
            db = db + jnp.sum(g, axis=0)
        return (dw, db), dh_c

    dw0 = jnp.zeros(w.shape, jnp.float32)
    db0 = jnp.zeros(w.shape[:1], jnp.float32) if with_bias else jnp.float32(0)
    vf = valid.astype(jnp.float32)
    (dw, db), dh = jax.lax.scan(
        body, (dw0, db0), (h.reshape(n, c, hid), labels.reshape(n, c),
                           vf.reshape(n, c), ct.reshape(n, c)))
    return (dh.reshape(t, hid).astype(h.dtype), dw.astype(w.dtype),
            db.astype(b.dtype) if with_bias else None, None, None)


def fused_linear_cross_entropy(h, weight, labels, chunk_size=None,
                               bias=None, ignore_index=None, name=None):
    """Per-token CE of (h @ weight^T [+ bias]) vs labels WITHOUT
    materializing the (tokens, vocab) logits between forward and backward.

    h (..., H) hidden states, weight (V, H) (the tied embedding layout),
    labels (...) int.  Returns per-token losses shaped like labels;
    positions where labels == ignore_index get loss 0 and contribute no
    gradient (the BERT MLM ignore_index=-100 contract — divide by the
    valid count yourself for the mean)."""
    if chunk_size is None:
        import os
        chunk_size = int(os.environ.get("PDTPU_FUSEDCE_CHUNK", "2048"))
    lead = unwrap(labels).shape

    def raw(hv, wv, lv, bv=None):
        flat_l = lv.reshape(-1)
        if ignore_index is not None:
            valid = flat_l != ignore_index
            flat_l = jnp.where(valid, flat_l, 0)
        else:
            valid = jnp.ones(flat_l.shape, bool)
        flat = _flce(hv.reshape(-1, hv.shape[-1]), wv, bv, flat_l,
                     valid, chunk_size)
        return flat.reshape(lead)

    if bias is not None:
        return dispatch("fused_linear_cross_entropy", raw, h, weight,
                        labels, bias)
    return dispatch("fused_linear_cross_entropy", raw, h, weight, labels)

_flce.defvjp(_flce_fwd, _flce_bwd)


def fused_pool_linear_cross_entropy(features, weight, labels, bias=None,
                                    chunk_size=None, data_format="NCHW",
                                    name=None):
    """Classifier-tail fusion: global-avg-pool -> linear -> softmax-CE as
    one op, per-sample losses out.

    features: (N, C, H, W) logical NCHW, or (N, H, W, C) with
    data_format="NHWC" — a model built channels-last natively passes its
    own data_format; a physically-NHWC layout-TAGGED tensor is also
    detected and pooled in place (no boundary transpose);
    weight: (C, classes) — the paddle Linear layout; labels: (N,) int.
    The feature map is reduced to (N, C) inside the op and the logits ride
    the chunked `_flce` machinery, so neither the full-rank feature map
    nor the (N, classes) logits round-trip HBM between forward and
    backward.  Returns per-sample CE losses shaped (N,)."""
    from ..core import layout as _layout
    if chunk_size is None:
        import os
        chunk_size = int(os.environ.get("PDTPU_FUSEDCE_CHUNK", "2048"))
    channels_last = (_layout.tag_of(features) == _layout.NHWC
                     or data_format == "NHWC")

    def raw(feat, wv, lv, bv=None):
        axes = (1, 2) if channels_last else (2, 3)
        h = jnp.mean(feat.astype(jnp.float32), axis=axes).astype(feat.dtype)
        flat_l = lv.reshape(-1)
        valid = jnp.ones(flat_l.shape, bool)
        return _flce(h, wv.T, bv, flat_l.astype(jnp.int32), valid,
                     chunk_size)

    if bias is not None:
        return dispatch("fused_pool_linear_cross_entropy", raw, features,
                        weight, labels, bias)
    return dispatch("fused_pool_linear_cross_entropy", raw, features,
                    weight, labels)
