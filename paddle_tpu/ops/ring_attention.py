"""Ring attention — sequence/context parallelism over the "sp" mesh axis.

The reference has NO sequence parallelism (SURVEY.md §2.3: its long-sequence
story is LoD batching + cudnn RNNs); this is the TPU-native long-context
upgrade the spec calls first-class: q/k/v are sharded along the sequence axis,
each device computes blockwise attention against the k/v block it currently
holds while the blocks rotate around the ring (`lax.ppermute` over ICI),
with online-softmax accumulation so the full (S, S) score matrix never
exists.  Compute overlaps the ppermute transfer (XLA schedules the ring
collective concurrently with the einsum).

Differentiable end-to-end: jax transposes ppermute/scan, so jax.grad gives
the backward ring for free.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ring_attention_local(q, k, v, n_sp: int, s_local: int, causal: bool,
                          axis_name: str):
    """Per-device body: q/k/v are (b, s_local, h, d) local shards."""
    me = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2).astype(jnp.float32) * scale   # (b,h,sq,d)
    b, h, sq, d = qt.shape

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, sq, 1), jnp.float32)
    perm = [(i, (i + 1) % n_sp) for i in range(n_sp)]

    def step(carry, j):
        k_blk, v_blk, acc, m, l = carry
        src = (me - j) % n_sp       # which global block k_blk holds
        kt = jnp.swapaxes(k_blk, 1, 2).astype(jnp.float32)
        vt = jnp.swapaxes(v_blk, 1, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt)
        if causal:
            q_pos = me * s_local + lax.broadcasted_iota(
                jnp.int32, (sq, s_local), 0)
            k_pos = src * s_local + lax.broadcasted_iota(
                jnp.int32, (sq, s_local), 1)
            s = jnp.where((q_pos >= k_pos)[None, None], s, -1e30)
        m_new = jnp.maximum(m, s.max(-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vt)
        # rotate k/v blocks around the ring
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, acc, m_new, l), None

    (_, _, acc, m, l), _ = lax.scan(
        step, (k, v, acc0, m0, l0), jnp.arange(n_sp))
    out = acc / jnp.maximum(l, 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)  # (b, s_local, h, d)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = False,
                   axis_name: str = "sp", batch_axis: Optional[str] = "dp"):
    """Full-array API: q/k/v (batch, seq, heads, head_dim); seq must divide
    the sp axis size.  Used under jit; shards seq over `axis_name` and batch
    over `batch_axis`, returns the attention output with the same layout.
    """
    n_sp = mesh.shape[axis_name]
    s = q.shape[1]
    if s % n_sp:
        raise ValueError(f"seq len {s} not divisible by sp={n_sp}")
    s_local = s // n_sp
    bspec = batch_axis if (batch_axis and mesh.shape.get(batch_axis, 1) > 1
                           ) else None
    spec = P(bspec, axis_name, None, None)

    fn = jax.shard_map(
        partial(_ring_attention_local, n_sp=n_sp, s_local=s_local,
                causal=causal, axis_name=axis_name),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
