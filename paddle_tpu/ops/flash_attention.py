"""Flash attention for TPU (pallas).

Replaces the reference's fused attention CUDA kernel
(paddle/fluid/operators/fused/multihead_matmul_op.cu) with an online-softmax
blocked kernel that never materializes the (seq, seq) score matrix in HBM —
the key to long-context MFU on TPU (see /opt/skills/guides/pallas_guide.md).

`flash_attention_bshd` returns None when the kernel doesn't apply (wrong
platform/shape); callers fall back to the XLA-fused naive path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def flash_attention_bshd(q, k, v, causal=False):
    """q/k/v: (batch, seq, heads, head_dim). Returns same layout, or None."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if not _on_tpu():
        return None
    if d not in (64, 128, 256):
        return None
    if sq % 128 != 0 or sk % 128 != 0:
        return None
    if k.shape[2] != h:  # grouped-query: caller expands kv heads first
        return None
    try:
        qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
        kt = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
        vt = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)
        out = _flash_bhsd(qt, kt, vt, causal)
        return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)
    except Exception:
        return None


@functools.partial(jax.jit, static_argnums=(3,))
def _flash_bhsd(q, k, v, causal):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    # block sizes must DIVIDE the seq lens (callers guarantee multiples of
    # 128) or whole key blocks would be dropped / query rows left unwritten
    blk_q = next(b for b in (512, 256, 128) if sq % b == 0)
    blk_k = next(b for b in (512, 256, 128) if sk % b == 0)
    n_k = sk // blk_k
    scale = 1.0 / math.sqrt(d)
    # causal offset for sq != sk (kv-cache decode): query i sees keys
    # <= i + (sk - sq), matching the naive path's tril(..., k=sk-sq)
    causal_off = sk - sq

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        qi = pl.program_id(1)
        ki = pl.program_id(2)

        @pl.when(ki == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, -1e30)
            l_ref[...] = jnp.zeros_like(l_ref)

        def _compute():
            qb = q_ref[0].astype(jnp.float32) * scale
            kb = k_ref[0].astype(jnp.float32)
            s = jax.lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if causal:
                rows = qi * blk_q + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 0)
                cols = ki * blk_k + jax.lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1)
                s = jnp.where(rows + causal_off >= cols, s, -1e30)
            m_prev = m_ref[...]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_cur)
            alpha = jnp.exp(m_prev - m_cur)
            l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
            m_ref[...] = m_cur
            vb = v_ref[0].astype(jnp.float32)
            acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
                p, vb, preferred_element_type=jnp.float32)

        if causal:
            @pl.when((ki * blk_k) <= (qi * blk_q + blk_q - 1 + causal_off))
            def _go():
                _compute()
        else:
            _compute()

        @pl.when(ki == n_k - 1)
        def _finish():
            o_ref[0] = (acc_ref[...] / jnp.maximum(l_ref[...], 1e-30)
                        ).astype(o_ref.dtype)

    grid = (bh, sq // blk_q, n_k)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, blk_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((blk_q, d), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
        ],
    )(q, k, v)
