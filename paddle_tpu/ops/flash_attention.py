"""Flash attention (forward + backward) for TPU via pallas.

Replaces the reference's fused attention CUDA kernels
(paddle/fluid/operators/fused/multihead_matmul_op.cu, fused_attention) with an
online-softmax blocked kernel pair that never materializes the (seq, seq)
score matrix in HBM — the key to long-context MFU on TPU.

Design notes (see /opt/skills/guides/pallas_guide.md):
- q/k/v stay in their input dtype (bf16 under AMP) going into the MXU dots
  with `preferred_element_type=f32` accumulation; only the softmax state is
  kept in f32.
- **Natural layout**: the kernels read (B, S, H*D) blocks straight out of the
  model's (batch, seq, heads, head_dim) tensors — no (B,S,H,D)->(B*H,S,D)
  transpose through HBM on either side.  A grid step owns a GROUP of G heads
  (G*D lanes, 128 <= G*D <= 512) and loops over them in-register: per-head
  (s, d) matmuls at d=64 run at MXU row-rate, so amortizing every load/store
  across a head group is worth ~1.8x over a head-per-step grid (measured on
  v5e at BERT-large shapes).
- The backward is the FlashAttention-2 recompute scheme: the forward saves
  only O and the per-row logsumexp; one merged backward kernel recomputes the
  score blocks and produces dQ partials, dK and dV in a single pass.
- Dropout is applied *inside* the kernel from the TPU hardware PRNG re-seeded
  per (head, q-block, k-block), so the keep mask is bit-identical between
  forward and backward regardless of grid order.  Under `interpret=True`
  (CPU CI) a murmur-style hash of absolute coordinates replaces the PRNG.
- Masking: `causal`, an additive per-key bias (B, Sk) covering padding masks,
  and q/kv segment ids (packed-sequence masking) are fused into the kernel.

`flash_attention_bshd` returns None when the kernel doesn't apply (wrong
platform/shape); callers fall back to the XLA-fused naive path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False  # tests flip this to run the kernels via the interpreter

from ._compat import CompilerParams as _CompilerParams

_NEG_INF = -1e30


def _available() -> bool:
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _env_int(name):
    import os
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None  # tuning knob: garbage falls back to the heuristic


def _block(size: int) -> int:
    override = _env_int("PDTPU_FLASH_BLOCK")
    if override in (128, 256, 512) and size % override == 0:
        return override
    return next(b for b in (512, 256, 128) if size % b == 0)


def _head_group(h: int, d: int):
    """Heads per grid step: largest divisor of h with 128 <= g*d <= 512,
    preferring g*d == 256 (the measured sweet spot on v5e).  Falls back to
    folding ALL heads into one group — a block whose lane dim equals the
    array's full last dim is exempt from the 128-divisibility rule."""
    # lane width g*d must be a multiple of 128 (or the array's full last
    # dim h*d, the one exemption Mosaic grants) — h=6,d=64 must pick g=2
    # (128 lanes), not g=3 (192 lanes, unlowerable)
    override = _env_int("PDTPU_FLASH_GROUP")
    if override and h % override == 0 and (override * d) % 128 == 0 \
            and 128 <= override * d <= 1024:
        # the override must still satisfy Mosaic's 128-lane constraint —
        # an unlowerable g would fail with an opaque kernel error
        return override
    cands = [g for g in range(1, h + 1)
             if h % g == 0 and 128 <= g * d <= 512 and (g * d) % 128 == 0]
    if not cands:
        return h  # full fold: block last dim == array last dim is allowed
    return min(cands, key=lambda g: (abs(g * d - 256), -g))


def flash_attention_bshd(q, k, v, causal=False, bias=None, q_segment_ids=None,
                         kv_segment_ids=None, dropout_p=0.0, dropout_seed=None):
    """q/k/v: (batch, seq, heads, head_dim). Returns same layout, or None.

    bias: additive f32 per-key bias (batch, seq_k) — the padding-mask case.
    q_segment_ids / kv_segment_ids: int32 (batch, seq) packed-sequence ids;
    positions attend only within equal ids.
    dropout_p with dropout_seed (int32 array shape (1,)): in-kernel attention
    probability dropout.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    if not _available():
        return None
    if d not in (64, 128, 256):
        return None
    if sq % 128 != 0 or sk % 128 != 0:
        return None
    if k.shape[2] != h:  # grouped-query: caller expands kv heads first
        return None
    if dropout_p > 0.0 and dropout_seed is None:
        return None
    if (q_segment_ids is None) != (kv_segment_ids is None):
        return None
    g = _head_group(h, d)
    # natural layout: (B, S, H, D) -> (B, S, H*D) is a free reshape
    qt = q.reshape(b, sq, h * d)
    kt = k.reshape(b, sk, h * d)
    vt = v.reshape(b, sk, h * d)
    # reshape mask inputs so every pallas block satisfies the TPU tiling
    # rule (last two dims divisible by (8,128) or equal to the array's):
    # per-key vectors ride the lane axis as (B, 1, Sk), per-query ids the
    # sublane axis as (B, Sq, 1)
    if bias is not None:
        bias = bias.astype(jnp.float32)[:, None, :]
    if q_segment_ids is not None:
        q_segment_ids = q_segment_ids.astype(jnp.int32)[:, :, None]
    if kv_segment_ids is not None:
        kv_segment_ids = kv_segment_ids.astype(jnp.int32)[:, None, :]
    if dropout_seed is None:
        dropout_seed = jnp.zeros((1,), jnp.int32)
    if dropout_p > 0.0:
        _hw_prng_available()  # resolve the bit-source before kernel trace
    out = _flash(qt, kt, vt, bias, q_segment_ids, kv_segment_ids,
                 dropout_seed, bool(causal), float(dropout_p), h, g)
    return out.reshape(b, sq, h, d)


# ---------------------------------------------------------------------------
# in-kernel dropout.
#
# On TPU: the hardware PRNG, re-seeded per (seed, bh, qi, ki) block so the
# keep mask is identical wherever the block is recomputed (fwd kernel and the
# merged bwd kernel iterate blocks in different grid orders).
# Under interpret=True (CPU CI): a murmur3-style hash of absolute
# coordinates — the TPU PRNG primitives don't run in the interpreter.


_HW_PRNG: bool = None  # lazily probed: does this backend lower pltpu.prng_*?


def _hw_prng_available() -> bool:
    """Compile-probe the TPU PRNG primitives once; fall back to the hash
    bit-source (which lowers everywhere) if they don't lower here."""
    global _HW_PRNG
    if _HW_PRNG is None:
        if _INTERPRET:
            return False
        try:
            def _probe_kernel(s_ref, o_ref):
                pltpu.prng_seed(s_ref[0], jnp.int32(1))
                o_ref[...] = pltpu.prng_random_bits((8, 128)).astype(
                    jnp.int32)
            out = pl.pallas_call(
                _probe_kernel,
                out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
            )(jnp.zeros((1,), jnp.int32))
            jax.block_until_ready(out)
            _HW_PRNG = True
        except Exception:
            _HW_PRNG = False
    return _HW_PRNG


def _keep_mask(seed_ref, bh, qi, ki, blk_q, blk_k, dropout_p):
    thresh = min(int(dropout_p * 4294967296.0), 4294967295)
    if not _INTERPRET and _HW_PRNG:
        # hardware seeding takes at most 2 words: pack (seed, bh) and
        # (qi, ki) — grid coords are far below 2^15 so the pair is unique.
        # -1640531615 == 0x9E3779B1 as int32
        pltpu.prng_seed(seed_ref[0] + bh * jnp.int32(-1640531615),
                        qi * jnp.int32(0x10001) + ki)
        bits = pltpu.prng_random_bits((blk_q, blk_k))
        return bits.astype(jnp.uint32) >= jnp.uint32(thresh)
    rows, cols = _coords(qi, ki, blk_q, blk_k)
    x = (rows.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         ^ cols.astype(jnp.uint32) * jnp.uint32(0x85EBCA77))
    x = x ^ (seed_ref[0].astype(jnp.uint32)
             + bh.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x >= jnp.uint32(thresh)


def _coords(qi, ki, blk_q, blk_k):
    rows = qi * blk_q + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
    cols = ki * blk_k + jax.lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
    return rows, cols


def _mask_specs(has_bias, has_seg, blk_q, blk_k, q_pos):
    """BlockSpecs for the optional [bias, qseg, kseg] inputs (in that order).
    `q_pos` says which of the two non-(batch/group) grid axes (0 or 1) walks
    the q blocks. Per-key inputs are (B, 1, Sk), per-query ones (B, Sq, 1)."""
    k_pos = 1 - q_pos

    def spec_k(pos):
        return pl.BlockSpec(
            (1, 1, blk_k),
            lambda b, g, a1, a2, s, _p=pos: (b, 0, (a1, a2)[_p]))

    def spec_q(pos):
        return pl.BlockSpec(
            (1, blk_q, 1),
            lambda b, g, a1, a2, s, _p=pos: (b, (a1, a2)[_p], 0))

    out = []
    if has_bias:
        out.append(spec_k(k_pos))
    if has_seg:
        out.append(spec_q(q_pos))
        out.append(spec_k(k_pos))
    return out


def _masked_scores(q_hd, k_hd, bias_ref, qseg_ref, kseg_ref, qi, ki,
                   blk_q, blk_k, scale, causal, causal_off):
    """One (blk_q, blk_k) score block for one head with all masks (f32)."""
    s = jax.lax.dot_general(q_hd, k_hd, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if bias_ref is not None:
        s = s + bias_ref[0]  # (1, blk_k) broadcast over rows
    if causal or qseg_ref is not None:
        rows, cols = _coords(qi, ki, blk_q, blk_k)
        if causal:
            s = jnp.where(rows + causal_off >= cols, s, _NEG_INF)
        if qseg_ref is not None:
            # (blk_q, 1) == (1, blk_k) -> (blk_q, blk_k)
            s = jnp.where(qseg_ref[0] == kseg_ref[0], s, _NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward


def _fwd_kernel(seed_ref, *refs, has_bias, has_seg, causal, dropout_p,
                blk_q, blk_k, n_k, scale, causal_off, heads, hg):
    it = iter(refs)
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    bias_ref = next(it) if has_bias else None
    qseg_ref = next(it) if has_seg else None
    kseg_ref = next(it) if has_seg else None
    o_ref, lse_ref = next(it), next(it)
    if n_k > 1:
        acc_ref, m_ref, l_ref = next(it), next(it), next(it)

    b, g, qi, ki = (pl.program_id(0), pl.program_id(1), pl.program_id(2),
                    pl.program_id(3))
    d = q_ref.shape[-1] // hg

    if n_k > 1:
        @pl.when(ki == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

    def _head(h):
        sl = slice(h * d, (h + 1) * d)
        s = _masked_scores(q_ref[0][:, sl], k_ref[0][:, sl], bias_ref,
                           qseg_ref, kseg_ref, qi, ki, blk_q, blk_k,
                           scale, causal, causal_off)
        bh = b * jnp.int32(heads) + g * jnp.int32(hg) + jnp.int32(h)
        if n_k == 1:
            m = jnp.max(s, axis=1, keepdims=True)
            p = jnp.exp(s - m)
            l = jnp.maximum(jnp.sum(p, axis=1, keepdims=True), 1e-30)
            if dropout_p > 0.0:
                keep = _keep_mask(seed_ref, bh, qi, ki, blk_q, blk_k,
                                  dropout_p)
                p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
            o = jax.lax.dot(p.astype(v_ref.dtype), v_ref[0][:, sl],
                            preferred_element_type=jnp.float32) / l
            return o.astype(o_ref.dtype), m + jnp.log(l)
        # online-softmax path (multiple k blocks)
        hsl = slice(h, h + 1)
        m_prev = m_ref[:, hsl]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_cur)
        alpha = jnp.exp(m_prev - m_cur)
        l_ref[:, hsl] = l_ref[:, hsl] * alpha + jnp.sum(p, axis=1,
                                                        keepdims=True)
        m_ref[:, hsl] = m_cur
        if dropout_p > 0.0:
            keep = _keep_mask(seed_ref, bh, qi, ki, blk_q, blk_k, dropout_p)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_p)), 0.0)
        acc_ref[:, sl] = acc_ref[:, sl] * alpha + jax.lax.dot(
            p.astype(v_ref.dtype), v_ref[0][:, sl],
            preferred_element_type=jnp.float32)
        return None, None

    def _compute():
        if n_k == 1:
            outs, lses = [], []
            for h in range(hg):
                o, lse = _head(h)
                outs.append(o)
                lses.append(lse)
            o_ref[0] = jnp.concatenate(outs, axis=1)
            lse_ref[0, 0] = jnp.concatenate(lses, axis=1)
        else:
            for h in range(hg):
                _head(h)

    if causal and n_k > 1:
        @pl.when(qi * blk_q + blk_q - 1 + causal_off >= ki * blk_k)
        def _go():
            _compute()
    else:
        _compute()

    if n_k > 1:
        @pl.when(ki == n_k - 1)
        def _finish():
            l = jnp.maximum(l_ref[...], 1e-30)
            d_ = q_ref.shape[-1] // hg
            parts = [(acc_ref[:, h * d_:(h + 1) * d_] / l[:, h:h + 1])
                     for h in range(hg)]
            o_ref[0] = jnp.concatenate(parts, axis=1).astype(o_ref.dtype)
            lse_ref[0, 0] = m_ref[...] + jnp.log(l)


def _fwd_impl(q, k, v, bias, qseg, kseg, seed, causal, dropout_p, heads, hg):
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // heads
    gd = hg * d
    n_hg = heads // hg
    blk_q, blk_k = _block(sq), _block(sk)
    n_q, n_k = sq // blk_q, sk // blk_k
    scale = 1.0 / math.sqrt(d)

    in_specs = [
        pl.BlockSpec((1, blk_q, gd), lambda b, g, i, j, s: (b, i, g)),
        pl.BlockSpec((1, blk_k, gd), lambda b, g, i, j, s: (b, j, g)),
        pl.BlockSpec((1, blk_k, gd), lambda b, g, i, j, s: (b, j, g)),
    ]
    inputs = [q, k, v]
    in_specs += _mask_specs(bias is not None, qseg is not None,
                            blk_q, blk_k, q_pos=0)
    if bias is not None:
        inputs.append(bias)
    if qseg is not None:
        inputs.extend([qseg, kseg])

    kernel = functools.partial(
        _fwd_kernel, has_bias=bias is not None, has_seg=qseg is not None,
        causal=causal, dropout_p=dropout_p, blk_q=blk_q, blk_k=blk_k,
        n_k=n_k, scale=scale, causal_off=sk - sq, heads=heads, hg=hg)

    scratch = []
    if n_k > 1:
        scratch = [
            pltpu.VMEM((blk_q, gd), jnp.float32),
            pltpu.VMEM((blk_q, hg), jnp.float32),
            pltpu.VMEM((blk_q, hg), jnp.float32),
        ]

    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_hg, n_q, n_k),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, blk_q, gd), lambda b, g, i, j, s: (b, i, g)),
                pl.BlockSpec((1, 1, blk_q, hg),
                             lambda b, g, i, j, s: (b, g, i, 0)),
            ],
            scratch_shapes=scratch,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
            jax.ShapeDtypeStruct((b, n_hg, sq, hg), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(seed, *inputs)
    return o, lse


# ---------------------------------------------------------------------------
# backward: ONE merged kernel (FlashAttention-2 recompute scheme).
#
# The score block s and p = exp(s - lse) are recomputed once per (k,q) block
# and feed dq, dk AND dv — half the exp/mask/dropout recompute of the classic
# two-kernel (dq grid / dkv grid) split.  dk/dv accumulate in VMEM across the
# inner q axis; dq cannot (its output block is revisited non-consecutively on
# TPU), so each grid step writes a per-k-block dq partial and XLA sums the
# n_k partials afterwards — free when n_k == 1, O(n_k · |dq|) HBM otherwise,
# still far cheaper than a second score recompute pass.


def _bwd_kernel(seed_ref, *refs, has_bias, has_seg, causal, dropout_p,
                blk_q, blk_k, n_q, scale, causal_off, heads, hg):
    it = iter(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = (
        next(it), next(it), next(it), next(it), next(it), next(it))
    bias_ref = next(it) if has_bias else None
    qseg_ref = next(it) if has_seg else None
    kseg_ref = next(it) if has_seg else None
    dqp_ref, dk_ref, dv_ref = next(it), next(it), next(it)
    dbias_ref = next(it) if has_bias else None
    dk_acc, dv_acc = next(it), next(it)
    db_acc = next(it) if has_bias else None

    b, g, ki, qi = (pl.program_id(0), pl.program_id(1), pl.program_id(2),
                    pl.program_id(3))
    d = q_ref.shape[-1] // hg

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)
        if has_bias:
            db_acc[...] = jnp.zeros_like(db_acc)

    def _compute():
        dq_parts = []
        for h in range(hg):
            sl = slice(h * d, (h + 1) * d)
            s = _masked_scores(q_ref[0][:, sl], k_ref[0][:, sl], bias_ref,
                               qseg_ref, kseg_ref, qi, ki, blk_q, blk_k,
                               scale, causal, causal_off)
            p = jnp.exp(s - lse_ref[0, 0][:, h:h + 1])
            dpd = jax.lax.dot_general(
                do_ref[0][:, sl], v_ref[0][:, sl], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if dropout_p > 0.0:
                bh = (b * jnp.int32(heads) + g * jnp.int32(hg)
                      + jnp.int32(h))
                keep = _keep_mask(seed_ref, bh, qi, ki, blk_q, blk_k,
                                  dropout_p)
                inv = 1.0 / (1.0 - dropout_p)
                pd = jnp.where(keep, p * inv, 0.0)
                dp = jnp.where(keep, dpd * inv, 0.0)
            else:
                pd, dp = p, dpd
            dv_acc[:, sl] += jax.lax.dot_general(
                pd.astype(do_ref.dtype), do_ref[0][:, sl],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            ds = p * (dp - delta_ref[0, 0][:, h:h + 1])
            dk_acc[:, sl] += jax.lax.dot_general(
                ds.astype(q_ref.dtype), q_ref[0][:, sl],
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            if has_bias:  # d(bias_k) = sum over q rows of dS (heads summed)
                db_acc[...] += jnp.sum(ds, axis=0, keepdims=True)
            dq_parts.append((jax.lax.dot(
                ds.astype(k_ref.dtype), k_ref[0][:, sl],
                preferred_element_type=jnp.float32) * scale))
        dqp_ref[0, 0] = jnp.concatenate(dq_parts, axis=1).astype(
            dqp_ref.dtype)

    if causal:
        cond = qi * blk_q + blk_q - 1 + causal_off >= ki * blk_k

        @pl.when(cond)
        def _go():
            _compute()

        @pl.when(jnp.logical_not(cond))
        def _zero():  # this (k,q) partial must still be defined
            dqp_ref[0, 0] = jnp.zeros_like(dqp_ref[0, 0])
    else:
        _compute()

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)
        if has_bias:
            dbias_ref[0, 0] = db_acc[...]


def _bwd_impl(q, k, v, bias, qseg, kseg, seed, o, lse, do,
              causal, dropout_p, heads, hg):
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // heads
    gd = hg * d
    n_hg = heads // hg
    blk_q, blk_k = _block(sq), _block(sk)
    n_q, n_k = sq // blk_q, sk // blk_k
    scale = 1.0 / math.sqrt(d)
    causal_off = sk - sq

    # delta[b, s, h] = sum_d do*o, laid out (B, n_hg, Sq, hg) like lse
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).reshape(
        b, sq, heads, d).sum(-1).reshape(b, sq, n_hg, hg).transpose(
        0, 2, 1, 3)

    # grid (b, head group, k block, q block): dk/dv owned per outer k step,
    # dq written as per-k partials summed below
    kv_specs = [
        pl.BlockSpec((1, blk_q, gd), lambda b, g, j, i, s: (b, i, g)),  # q
        pl.BlockSpec((1, blk_k, gd), lambda b, g, j, i, s: (b, j, g)),  # k
        pl.BlockSpec((1, blk_k, gd), lambda b, g, j, i, s: (b, j, g)),  # v
        pl.BlockSpec((1, blk_q, gd), lambda b, g, j, i, s: (b, i, g)),  # do
        pl.BlockSpec((1, 1, blk_q, hg),
                     lambda b, g, j, i, s: (b, g, i, 0)),               # lse
        pl.BlockSpec((1, 1, blk_q, hg),
                     lambda b, g, j, i, s: (b, g, i, 0)),               # delta
    ]
    kv_extra = _mask_specs(bias is not None, qseg is not None,
                           blk_q, blk_k, q_pos=1)
    inputs = [q, k, v, do, lse, delta] + \
        ([] if bias is None else [bias]) + \
        ([] if qseg is None else [qseg, kseg])

    dqp_dtype = q.dtype if n_k == 1 else jnp.float32
    outs = pl.pallas_call(
        functools.partial(
            _bwd_kernel, has_bias=bias is not None,
            has_seg=qseg is not None, causal=causal, dropout_p=dropout_p,
            blk_q=blk_q, blk_k=blk_k, n_q=n_q, scale=scale,
            causal_off=causal_off, heads=heads, hg=hg),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, n_hg, n_k, n_q),
            in_specs=kv_specs + kv_extra,
            out_specs=[
                pl.BlockSpec((1, 1, blk_q, gd),
                             lambda b, g, j, i, s: (j, b, i, g)),   # dq part
                pl.BlockSpec((1, blk_k, gd),
                             lambda b, g, j, i, s: (b, j, g)),
                pl.BlockSpec((1, blk_k, gd),
                             lambda b, g, j, i, s: (b, j, g)),
            ] + ([pl.BlockSpec((1, 1, 1, blk_k),
                               lambda b, g, j, i, s: (b, g, 0, j))]
                 if bias is not None else []),
            scratch_shapes=[
                pltpu.VMEM((blk_k, gd), jnp.float32),
                pltpu.VMEM((blk_k, gd), jnp.float32),
            ] + ([pltpu.VMEM((1, blk_k), jnp.float32)]
                 if bias is not None else []),
        ),
        out_shape=[
            jax.ShapeDtypeStruct((n_k, b, sq, hd), dqp_dtype),
            jax.ShapeDtypeStruct((b, sk, hd), k.dtype),
            jax.ShapeDtypeStruct((b, sk, hd), v.dtype),
        ] + ([jax.ShapeDtypeStruct((b, n_hg, 1, sk), jnp.float32)]
             if bias is not None else []),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=_INTERPRET,
    )(seed, *inputs)
    dqp, dk, dv = outs[0], outs[1], outs[2]
    dq = dqp[0].astype(q.dtype) if n_k == 1 else \
        dqp.sum(axis=0).astype(q.dtype)
    dbias = None
    if bias is not None:  # per-(batch, head-group) key sums -> (B, 1, Sk)
        dbias = outs[3].sum(axis=1)
    return dq, dk, dv, dbias


# ---------------------------------------------------------------------------
# custom_vjp glue


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10))
def _flash(q, k, v, bias, qseg, kseg, seed, causal, dropout_p, heads, hg):
    o, _ = _fwd_impl(q, k, v, bias, qseg, kseg, seed, causal, dropout_p,
                     heads, hg)
    return o


def _flash_fwd(q, k, v, bias, qseg, kseg, seed, causal, dropout_p, heads, hg):
    o, lse = _fwd_impl(q, k, v, bias, qseg, kseg, seed, causal, dropout_p,
                       heads, hg)
    return o, (q, k, v, bias, qseg, kseg, seed, o, lse)


def _flash_bwd(causal, dropout_p, heads, hg, res, g):
    q, k, v, bias, qseg, kseg, seed, o, lse = res
    dq, dk, dv, dbias = _bwd_impl(q, k, v, bias, qseg, kseg, seed, o, lse, g,
                                  causal, dropout_p, heads, hg)
    dqseg = None if qseg is None else np.zeros(qseg.shape, jax.dtypes.float0)
    dkseg = None if kseg is None else np.zeros(kseg.shape, jax.dtypes.float0)
    dseed = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dbias, dqseg, dkseg, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)
