"""Paged attention: decode attention against a block-pooled KV cache.

The serving engine's paged KV pool (serving/kv_pool.py) stores KV as
``(num_blocks, block_size, heads, head_dim)`` per layer, with each slot
owning an indirection table of block ids.  This module is the device
side of that design:

- **gather / scatter / scrub primitives** — the three jnp operations the
  compiled serving decode/verify/prefill programs are built from:
  ``gather_block_rows`` materializes one slot's contiguous KV view from
  its block table (a single XLA gather), ``scatter_block_rows`` writes
  freshly produced KV rows back through the table (a single scatter;
  sentinel ids drop, so inactive slots and warmup write nothing), and
  ``scrub_blocks`` zeroes blocks the moment a slot first enters them
  (the scrub-on-recycle guarantee — a re-served block is erased in the
  same program that first writes it).  On CPU the gather fallback is
  also the engine's attention path: reconstructing the contiguous
  ``(T, heads, head_dim)`` view and running the model's own
  ``forward_fixed`` keeps paged streams BIT-IDENTICAL to the fixed-pool
  engine and to solo generate — the gathered array holds exactly the
  values the fixed row would, so every downstream float op is the same.
- **``paged_attention``** — the standalone op for one decode query
  against one slot's table: jnp gather fallback everywhere, and a
  pallas TPU kernel that never materializes the contiguous view — the
  block table rides in as a scalar-prefetch operand and the grid DMAs
  exactly the live blocks HBM->VMEM, accumulating flash-style online
  softmax across blocks (the vLLM PagedAttention structure; design
  notes /opt/skills/guides/pallas_guide.md).  The kernel is the TPU
  fast path: gather-free, O(live blocks) HBM traffic instead of
  O(max_len).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["gather_block_rows", "scatter_block_rows", "scrub_blocks",
           "paged_attention"]

_INTERPRET = False  # tests flip this to run the kernel via the interpreter


# ---------------------------------------------------------------------------
# block-pool primitives (used inside the compiled serving programs)
# ---------------------------------------------------------------------------

def gather_block_rows(pool, table):
    """(num_blocks, block_size, *rest) pool + (nb,) block table ->
    (nb * block_size, *rest) contiguous rows, one XLA gather.  Sentinel
    (out-of-range) table entries clip to the last block — their rows are
    only ever read under the attention mask, never trusted."""
    blocks = jnp.take(pool, table, axis=0, mode="clip")
    return blocks.reshape((blocks.shape[0] * blocks.shape[1],)
                          + blocks.shape[2:])


def scatter_block_rows(pool, block_ids, offsets, rows):
    """Write rows[i] -> pool[block_ids[i], offsets[i]] in one scatter.
    Out-of-range block ids (the allocator's sentinel) are DROPPED — the
    engine routes inactive slots, finished-run tail iterations, and
    warmup through the sentinel so they write nothing.  Distinct live
    slots never collide: their tables are disjoint by construction."""
    return pool.at[block_ids, offsets].set(rows.astype(pool.dtype),
                                           mode="drop")


def scrub_blocks(pool, block_ids):
    """Zero whole blocks (sentinel ids dropped).  Issued by the decode/
    verify programs for every block a slot ENTERS (write offset 0) before
    the row write: a recycled block is erased by the same program that
    first reuses it, so no prior tenant's KV survives re-serving.  Safe
    by construction: a block's first row is the entering position, so
    every committed row of the entering slot lives in earlier blocks."""
    return pool.at[block_ids].set(0, mode="drop")


# ---------------------------------------------------------------------------
# standalone paged attention op
# ---------------------------------------------------------------------------

def _available() -> bool:
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _fallback(q, kpool, vpool, table, pos):
    """jnp gather path: reconstruct the contiguous view, masked softmax.
    Bit-compatible with the fixed-pool engine's attention (same values in
    the gathered buffer -> same float ops)."""
    k = gather_block_rows(kpool, table).astype(jnp.float32)  # (T, H, D)
    v = gather_block_rows(vpool, table).astype(jnp.float32)
    d = q.shape[-1]
    s = jnp.einsum("hd,thd->ht", q.astype(jnp.float32), k) / jnp.sqrt(
        jnp.float32(d))
    t_idx = jnp.arange(k.shape[0])
    s = jnp.where((t_idx <= pos)[None, :], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("ht,thd->hd", p, v).astype(q.dtype)


def _kernel(table_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
            m_scr, l_scr, acc_scr, *, block_size):
    """One grid step = one block of one slot's table: online-softmax
    accumulate q against the DMA'd (block_size, H, D) KV block."""
    i = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(i == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[...].astype(jnp.float32)                    # (H, D)
    kb = k_ref[0].astype(jnp.float32)                     # (bs, H, D)
    vb = v_ref[0].astype(jnp.float32)
    d = q.shape[-1]
    s = jnp.einsum("hd,bhd->hb", q, kb) / jnp.sqrt(jnp.float32(d))
    # rows of this block past the write position are dead
    row = i * block_size + jax.lax.broadcasted_iota(jnp.int32,
                                                    s.shape, 1)
    s = jnp.where(row <= pos_ref[0], s, -jnp.inf)

    m_prev = m_scr[...][:, 0]                             # (H,)
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    # all-masked blocks keep m at -inf; exp(-inf - -inf) guards below
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_new), 0.0)
    p = jnp.where(jnp.isfinite(s), jnp.exp(s - m_new[:, None]), 0.0)
    l_new = l_scr[...][:, 0] * alpha + p.sum(axis=1)
    acc = acc_scr[...] * alpha[:, None] + jnp.einsum("hb,bhd->hd", p, vb)
    m_scr[...] = m_new[:, None]
    l_scr[...] = l_new[:, None]
    acc_scr[...] = acc

    @pl.when(i == nb - 1)
    def _emit():
        denom = jnp.maximum(l_scr[...][:, 0], 1e-30)
        o_ref[...] = (acc_scr[...] / denom[:, None]).astype(o_ref.dtype)


def _pallas_paged_attention(q, kpool, vpool, table, pos):
    nb = table.shape[0]
    nb_pool = kpool.shape[0]
    bs = kpool.shape[1]
    h, d = q.shape

    def block_ix(i, table_ref, pos_ref):
        # same sentinel contract as the jnp fallback's mode="clip":
        # unallocated tail entries hold an out-of-range id — clamp the
        # DMA address into the pool (the position mask kills the rows)
        return (jnp.minimum(table_ref[i], nb_pool - 1), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # (table, pos) drive the block DMA
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((h, d), lambda i, table_ref, pos_ref: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bs, h, d), block_ix),
            pl.BlockSpec((1, bs, h, d), block_ix),
        ],
        out_specs=pl.BlockSpec((h, d),
                               lambda i, table_ref, pos_ref: (0, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((h, 1), jnp.float32),   # running max
            pltpu.VMEM((h, 1), jnp.float32),   # running denominator
            pltpu.VMEM((h, d), jnp.float32),   # weighted-V accumulator
        ],
    )
    return pl.pallas_call(
        functools.partial(_kernel, block_size=bs),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, d), q.dtype),
        interpret=_INTERPRET,
    )(table.astype(jnp.int32), jnp.asarray(pos, jnp.int32).reshape(1),
      q, kpool, vpool)


def paged_attention(q, kpool, vpool, table, pos):
    """Decode attention for ONE slot: query `q` (heads, head_dim) against
    the slot's paged KV — `kpool`/`vpool` (num_blocks, block_size, heads,
    head_dim), `table` (nb,) int32 block ids, `pos` the slot's current
    write position (rows > pos are masked; the row at `pos` must already
    be written).  vmap over slots for a batch.

    TPU (or `_INTERPRET`): the pallas kernel — block table as a
    scalar-prefetch operand, one (block_size, H, D) block DMA'd per grid
    step, flash-style online softmax across blocks; the contiguous KV
    view is never materialized.  Both paths accept the engine's real
    tables: out-of-range sentinel entries (unallocated tail blocks)
    clamp/clip into the pool and their rows die under the position
    mask.  Elsewhere: the jnp gather fallback (bit-compatible with the
    fixed-pool engine)."""
    if _available():
        return _pallas_paged_attention(q, kpool, vpool, table, pos)
    return _fallback(q, kpool, vpool, table, pos)
