"""Fused training-BatchNorm + activation + residual-add for TPU via pallas.

The r5 ResNet-50 bench decomposition (bench.py RESNET notes) showed the
step is bound not by conv rate but by the BN/elementwise HBM traffic:
~8 passes over 5.7 GB of bf16 activations (conv write, stats read,
normalize+relu write, next-conv read, plus the backward re-reads)
~= 55 ms of a 118 ms step.  XLA cannot fuse a training-mode BN chain
below its reduce/elementwise granularity, so this module does it by
hand (design notes: /opt/skills/guides/pallas_guide.md):

- forward = 2 HBM passes: one single-pass stats kernel (sum and
  sum-of-squares accumulated together, f32, per channel) + one apply
  kernel computing `act(x * a + b [+ residual])` with the per-channel
  affine folded on the host side of the trace;
- backward = 2 passes with a `custom_vjp` that RECOMPUTES x_hat and the
  activation mask from the saved input instead of re-reading saved
  normalized/pre-activation tensors: one reduce kernel for the
  d_gamma/d_beta sums, one elementwise kernel producing dx (and the
  residual gradient) from three per-channel coefficients;
- all per-channel math ((C,)-sized) runs as plain traced jnp — it is
  nanoseconds and keeps the kernels pure elementwise/reduce.

Data is handled channels-last as a free (M, C) = (N*H*W, C) reshape —
the layout `core.layout` puts conv-net activations in anyway.  On
non-TPU backends (tier-1 CI runs `JAX_PLATFORMS=cpu`) `bn_act_train`
automatically selects a pure-jnp reference with identical semantics;
tests flip `_INTERPRET` to run the kernels through the pallas
interpreter and check parity against that reference.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False  # tests flip this to run the kernels via the interpreter

from ._compat import CompilerParams as _CompilerParams


def _inside_checkpoint() -> bool:
    """Inside a jit.recompute_policy-wrapped subtree the custom_vjp /
    kernel paths would PIN their saved activations across the checkpoint
    boundary (jax cannot remat through a custom rule) — every public
    entry below falls back to its plain differentiable reference there
    and lets jax.checkpoint own the recompute."""
    from ..core import recompute as _rc
    return _rc.inside_checkpoint()

_ACTS = (None, "relu", "relu6")
# VMEM budget per (blk_m, C) block: keep each f32 buffer <= ~512 KB so the
# worst kernel (bwd dx: g, x, res in + dx, dres out) stays well under VMEM
_MAX_BLOCK_ELEMS = 1 << 17


def _available() -> bool:
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _block_m(m: int, c: int):
    """Largest divisor of m that is a multiple of 8 and fits the VMEM
    budget; None when m has no usable divisor (jnp fallback)."""
    cap = min(m, max(8, _MAX_BLOCK_ELEMS // max(c, 1)))
    cap -= cap % 8
    for blk in range(cap, 7, -8):
        if m % blk == 0:
            return blk
    return None


def _act_apply(z, act):
    if act == "relu":
        return jnp.maximum(z, 0.0)
    if act == "relu6":
        return jnp.clip(z, 0.0, 6.0)
    return z


def _act_apply_ref(z, act):
    """Activation for the differentiable references, in select form:
    identical values to `_act_apply`, but the VJP of `where` saves only
    the bool predicate — `maximum`/`clip` save their f32 operand, which
    pins a full-resolution f32 tensor per BN site across the fwd->bwd
    gap (inside jax.checkpoint interiors too, which is exactly the
    liveness `jit.recompute_policy` exists to bound)."""
    if act == "relu":
        return jnp.where(z > 0.0, z, 0.0)
    if act == "relu6":
        return jnp.where(z > 0.0, jnp.where(z < 6.0, z, 6.0), 0.0)
    return z


def _act_mask(z, act):
    if act == "relu":
        return z > 0.0
    if act == "relu6":
        return jnp.logical_and(z > 0.0, z < 6.0)
    return None


# ---------------------------------------------------------------------------
# pallas kernels.  x is viewed as (M, C); the grid walks M in blk_m rows.
# Per-channel vectors ride in one (8, C) f32 `coef` input:
#   row 0 = a  (gamma * invstd)        row 1 = b  (beta - mean * a)
#   row 2 = mean                       row 3 = invstd
#   row 4 = A, row 5 = B, row 6 = Cc   (backward dx coefficients)


def _stats_kernel(x_ref, sum_ref, sq_ref, *, n_m):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    xb = x_ref[...].astype(jnp.float32)
    sum_ref[...] += jnp.sum(xb, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(xb * xb, axis=0, keepdims=True)


def _apply_kernel(*refs, act, has_res, dual=False):
    it = iter(refs)
    x_ref, coef_ref = next(it), next(it)
    res_ref = next(it) if has_res else None
    coefr_ref = next(it) if dual else None
    y_ref = next(it)
    z = x_ref[...].astype(jnp.float32) * coef_ref[0:1] + coef_ref[1:2]
    if has_res:
        rb = res_ref[...].astype(jnp.float32)
        z = z + (rb * coefr_ref[0:1] + coefr_ref[1:2] if dual else rb)
    y_ref[...] = _act_apply(z, act).astype(y_ref.dtype)


def _recompute_z(x_ref, coef_ref, res_ref, coefr_ref, has_res, dual):
    z = x_ref[...].astype(jnp.float32) * coef_ref[0:1] + coef_ref[1:2]
    if has_res:
        rb = res_ref[...].astype(jnp.float32)
        z = z + (rb * coefr_ref[0:1] + coefr_ref[1:2] if dual else rb)
    return z


def _bwd_reduce_kernel(*refs, act, has_res, dual=False):
    it = iter(refs)
    g_ref, x_ref, coef_ref = next(it), next(it), next(it)
    res_ref = next(it) if has_res else None
    coefr_ref = next(it) if dual else None
    sgz_ref, sgzx_ref = next(it), next(it)
    sgzr_ref = next(it) if dual else None
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sgz_ref[...] = jnp.zeros_like(sgz_ref)
        sgzx_ref[...] = jnp.zeros_like(sgzx_ref)
        if dual:
            sgzr_ref[...] = jnp.zeros_like(sgzr_ref)

    xb = x_ref[...].astype(jnp.float32)
    gz = g_ref[...].astype(jnp.float32)
    if act is not None:
        z = _recompute_z(x_ref, coef_ref, res_ref, coefr_ref, has_res, dual)
        gz = jnp.where(_act_mask(z, act), gz, 0.0)
    xhat = (xb - coef_ref[2:3]) * coef_ref[3:4]
    sgz_ref[...] += jnp.sum(gz, axis=0, keepdims=True)
    sgzx_ref[...] += jnp.sum(gz * xhat, axis=0, keepdims=True)
    if dual:
        rhat = (res_ref[...].astype(jnp.float32) - coefr_ref[2:3]) \
            * coefr_ref[3:4]
        sgzr_ref[...] += jnp.sum(gz * rhat, axis=0, keepdims=True)


def _bwd_dx_kernel(*refs, act, has_res, dual=False):
    it = iter(refs)
    g_ref, x_ref, coef_ref = next(it), next(it), next(it)
    res_ref = next(it) if has_res else None
    coefr_ref = next(it) if dual else None
    dx_ref = next(it)
    dres_ref = next(it) if has_res else None
    xb = x_ref[...].astype(jnp.float32)
    gz = g_ref[...].astype(jnp.float32)
    if act is not None:
        z = _recompute_z(x_ref, coef_ref, res_ref, coefr_ref, has_res, dual)
        gz = jnp.where(_act_mask(z, act), gz, 0.0)
    dx = coef_ref[4:5] * gz + coef_ref[5:6] + coef_ref[6:7] * xb
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if has_res:
        if dual:
            rb = res_ref[...].astype(jnp.float32)
            dres = coefr_ref[4:5] * gz + coefr_ref[5:6] + coefr_ref[6:7] * rb
            dres_ref[...] = dres.astype(dres_ref.dtype)
        else:
            dres_ref[...] = gz.astype(dres_ref.dtype)


def _row_spec(blk_m, c):
    return pl.BlockSpec((blk_m, c), lambda i: (i, 0))


def _const_spec(rows, c):
    return pl.BlockSpec((rows, c), lambda i: (0, 0))


def _coef(mean, invstd, gamma, beta, A=None, B=None, Cc=None):
    c = mean.shape[0]
    a = gamma * invstd
    b = beta - mean * a
    zero = jnp.zeros((c,), jnp.float32)
    rows = [a, b, mean, invstd, A if A is not None else zero,
            B if B is not None else zero, Cc if Cc is not None else zero,
            zero]
    return jnp.stack([r.astype(jnp.float32) for r in rows])


def _run_stats(x2, blk_m):
    m, c = x2.shape
    n_m = m // blk_m
    return pl.pallas_call(
        functools.partial(_stats_kernel, n_m=n_m),
        grid=(n_m,),
        in_specs=[_row_spec(blk_m, c)],
        out_specs=[_const_spec(1, c), _const_spec(1, c)],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(x2)


def _run_apply(x2, coef, res2, act, blk_m, coefr=None):
    m, c = x2.shape
    dual = coefr is not None
    inputs = [x2, coef] + ([res2] if res2 is not None else []) + \
        ([coefr] if dual else [])
    in_specs = [_row_spec(blk_m, c), _const_spec(8, c)] + \
        ([_row_spec(blk_m, c)] if res2 is not None else []) + \
        ([_const_spec(8, c)] if dual else [])
    return pl.pallas_call(
        functools.partial(_apply_kernel, act=act, has_res=res2 is not None,
                          dual=dual),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=_row_spec(blk_m, c),
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_INTERPRET,
    )(*inputs)


def _run_bwd_reduce(g2, x2, coef, res2, act, blk_m, coefr=None):
    m, c = x2.shape
    dual = coefr is not None
    inputs = [g2, x2, coef] + ([res2] if res2 is not None else []) + \
        ([coefr] if dual else [])
    in_specs = [_row_spec(blk_m, c), _row_spec(blk_m, c),
                _const_spec(8, c)] + \
        ([_row_spec(blk_m, c)] if res2 is not None else []) + \
        ([_const_spec(8, c)] if dual else [])
    n_out = 3 if dual else 2
    outs = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, act=act,
                          has_res=res2 is not None, dual=dual),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=[_const_spec(1, c)] * n_out,
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32)] * n_out,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(*inputs)
    return outs if dual else (outs[0], outs[1])


def _run_bwd_dx(g2, x2, coef, res2, act, blk_m, coefr=None):
    m, c = x2.shape
    has_res = res2 is not None
    dual = coefr is not None
    inputs = [g2, x2, coef] + ([res2] if has_res else []) + \
        ([coefr] if dual else [])
    in_specs = [_row_spec(blk_m, c), _row_spec(blk_m, c),
                _const_spec(8, c)] + \
        ([_row_spec(blk_m, c)] if has_res else []) + \
        ([_const_spec(8, c)] if dual else [])
    out_specs = [_row_spec(blk_m, c)] + ([_row_spec(blk_m, c)] if has_res
                                         else [])
    out_shape = [jax.ShapeDtypeStruct((m, c), x2.dtype)] + \
        ([jax.ShapeDtypeStruct((m, c), res2.dtype)] if has_res else [])
    outs = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, act=act, has_res=has_res,
                          dual=dual),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_INTERPRET,
    )(*inputs)
    return (outs[0], outs[1]) if has_res else (outs[0], None)


# ---------------------------------------------------------------------------
# custom_vjp over the (M, C) view


def _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m):
    m = x2.shape[0]
    s, sq = _run_stats(x2, blk_m)
    mean = s[0] / m
    var = jnp.maximum(sq[0] / m - mean * mean, 0.0)
    invstd = jax.lax.rsqrt(var + eps)
    coef = _coef(mean, invstd, gamma, beta)
    y2 = _run_apply(x2, coef, res2, act, blk_m)
    return y2, mean, var, invstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bn_act_p(x2, gamma, beta, res2, eps, act, blk_m):
    y2, mean, var, _ = _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m)
    return y2, mean, var


def _bn_act_fwd(x2, gamma, beta, res2, eps, act, blk_m):
    y2, mean, var, invstd = _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m)
    return (y2, mean, var), (x2, gamma, beta, res2, mean, invstd)


def _bn_act_bwd(eps, act, blk_m, residuals, cts):
    x2, gamma, beta, res2, mean, invstd = residuals
    gy, gmean, gvar = cts
    m = x2.shape[0]
    gammaf = gamma.astype(jnp.float32)
    coef = _coef(mean, invstd, gammaf, beta)
    sgz, sgzx = _run_bwd_reduce(gy, x2, coef, res2, act, blk_m)
    sgz, sgzx = sgz[0], sgzx[0]
    # dx = c1*(gz - sgz/M - xhat*sgzx/M) + gmean/M + gvar*2*(x-mean)/M
    #    = A*gz + B + Cc*x   with the xhat/mean terms folded per channel
    c1 = gammaf * invstd
    k = -c1 * sgzx * invstd / m + 2.0 * gvar.astype(jnp.float32) / m
    A = c1
    B = -c1 * sgz / m + gmean.astype(jnp.float32) / m - k * mean
    Cc = k
    coef_dx = _coef(mean, invstd, gammaf, beta, A, B, Cc)
    dx2, dres2 = _run_bwd_dx(gy, x2, coef_dx, res2, act, blk_m)
    dgamma = sgzx.astype(gamma.dtype)
    dbeta = sgz.astype(beta.dtype)
    return dx2, dgamma, dbeta, dres2


_bn_act_p.defvjp(_bn_act_fwd, _bn_act_bwd)


# ---------------------------------------------------------------------------
# reference (pure jnp): same math, any channel axis, fully differentiable.
# Used on CPU / whenever the kernels don't apply, and as the test oracle.


def _ref_stats(x, axes):
    """Batch mean/var in f32 regardless of storage dtype.  The converts
    feed straight into reduces (single-consumer chains XLA input-fuses),
    so no full-tensor f32 copy materializes."""
    mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
    sq = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=axes)
    return mean, jnp.maximum(sq - mean * mean, 0.0)


def bn_act_reference(x, gamma, beta, eps=1e-5, act=None, residual=None,
                     channel_axis=-1):
    """Returns (y, batch_mean, batch_var) — f32 stats, biased variance.

    Every f32 upcast here is SINGLE-CONSUMER by construction (stats
    accumulate f32 inside the reduces via dtype=/square-chains; the
    normalize takes its own fresh upcast): a shared `xf` binding with
    three consumers materializes a full f32 copy of a bf16 activation in
    the optimized HLO — on the r50-b16 CPU step that convert churn alone
    was ~7 GB of XLA bytes accessed."""
    ch = channel_axis % x.ndim
    axes = tuple(i for i in range(x.ndim) if i != ch)
    mean, var = _ref_stats(x, axes)
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    a = (gamma.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).reshape(shape)
    b = beta.astype(jnp.float32).reshape(shape) - mean.reshape(shape) * a
    z = x.astype(jnp.float32) * a + b
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    return _act_apply_ref(z, act).astype(x.dtype), mean, var


# ---------------------------------------------------------------------------
# public entry


def bn_act_train(x, gamma, beta, eps=1e-5, act=None, residual=None,
                 channel_last=True):
    """Fused training BatchNorm + optional residual-add + activation.

    x: (N, ..., C) when channel_last else (N, C, ...); gamma/beta: (C,);
    residual: same shape as x or None; act in {None, "relu", "relu6"}.
    Returns (y, batch_mean_f32, batch_var_f32).  Selects the pallas
    kernel pair on TPU (or under `_INTERPRET`), the jnp reference
    otherwise — callers never need to know which ran.
    """
    if act not in _ACTS:
        raise ValueError(f"bn_act_train: unsupported activation {act!r}")
    ch = -1 if channel_last else 1
    if _inside_checkpoint():
        return bn_act_reference(x, gamma, beta, eps, act, residual, ch)
    use_kernel = (channel_last and _available() and x.ndim >= 2
                  and x.dtype in (jnp.float32, jnp.bfloat16)
                  and (residual is None or residual.shape == x.shape))
    if use_kernel:
        c = x.shape[-1]
        m = int(x.size) // c
        blk_m = _block_m(m, c)
        if blk_m is not None:
            x2 = x.reshape(m, c)
            res2 = None if residual is None else \
                residual.astype(x.dtype).reshape(m, c)
            y2, mean, var = _bn_act_p(x2, gamma, beta, res2, float(eps),
                                      act, blk_m)
            return y2.reshape(x.shape), mean, var
    # fallback: same math, but through the recompute-backward wrapper so
    # the CPU/odd-shape path has the kernel's memory discipline too (only
    # x/res saved; z and the act mask recomputed in the backward)
    if residual is None:
        return _ref1_p(x, gamma, beta, float(eps), act, ch)
    return _ref1_res_p(x, gamma, beta, residual, float(eps), act, ch)


# ---------------------------------------------------------------------------
# recompute-backward wrappers over the jnp reference.  jax.checkpoint-style:
# forward saves only the primal inputs; the backward re-runs the (XLA-fused)
# reference and pulls gradients through jax.vjp — so the fallback paths stop
# materializing z / activation masks between forward and backward, which is
# where the unfused CPU legs were spending their bytes-accessed.


def _ref_vjp(fn, primals, cts):
    _, vjp = jax.vjp(fn, *primals)
    return vjp(cts)


# recompute-backward wrappers over the jnp reference (jax.checkpoint-style:
# forward saves only the primal inputs; the backward re-runs the XLA-fused
# reference under jax.vjp, so no z / activation-mask tensors are stored
# between forward and backward).  On CPU XLA CSEs the recomputation with
# the forward, so this costs no extra bytes accessed there.


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ref1_p(x, gamma, beta, eps, act, ch):
    return bn_act_reference(x, gamma, beta, eps, act, None, ch)


def _ref1_fwd(x, gamma, beta, eps, act, ch):
    out = bn_act_reference(x, gamma, beta, eps, act, None, ch)
    return out, (x, gamma, beta)


def _ref1_bwd(eps, act, ch, res, cts):
    return _ref_vjp(lambda x, g, b: bn_act_reference(x, g, b, eps, act,
                                                     None, ch), res, cts)


_ref1_p.defvjp(_ref1_fwd, _ref1_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ref1_res_p(x, gamma, beta, residual, eps, act, ch):
    return bn_act_reference(x, gamma, beta, eps, act, residual, ch)


def _ref1_res_fwd(x, gamma, beta, residual, eps, act, ch):
    out = bn_act_reference(x, gamma, beta, eps, act, residual, ch)
    return out, (x, gamma, beta, residual)


def _ref1_res_bwd(eps, act, ch, res, cts):
    return _ref_vjp(lambda x, g, b, r: bn_act_reference(x, g, b, eps, act,
                                                        r, ch), res, cts)


_ref1_res_p.defvjp(_ref1_res_fwd, _ref1_res_bwd)


# ---------------------------------------------------------------------------
# pooled epilogue: BN + activation + max/avg pool as ONE op.  The pooled
# output is the only full-rank tensor that leaves the op — the normalized/
# activated full-resolution tensor never round-trips HBM (pallas writes the
# pooled block directly on TPU; the fallback recomputes it in the backward).


def _pool_norm(pool):
    """Normalize a pool spec to (kind, (kh,kw), (sh,sw), (ph,pw))."""
    kind, k, s, p = pool
    pair = lambda v: tuple(v) if isinstance(v, (tuple, list)) else \
        (int(v), int(v))
    if kind not in ("max", "avg"):
        raise ValueError(f"fused pool: unsupported kind {kind!r}")
    return (kind, pair(k), pair(s if s is not None else k), pair(p))


def fusable_pool_spec(layer, data_format="NCHW"):
    """(kind, kernel, stride, padding) when `layer` is a stock MaxPool2D
    the fused BN/act epilogue can express — exact type only (a subclass
    forward must run), no ceil_mode/return_mask, no registered hooks (the
    epilogue skips the layer's __call__, so hooks would silently stop
    firing), and no data_format disagreeing with the norm's
    (`data_format` is the layout the epilogue already runs in) — else
    None; the caller then runs the layer itself.  The one gate every
    conv-net block (ResNet stem, VGG runs) uses before folding its pool."""
    from ..nn.layer.pooling import MaxPool2D
    if type(layer) is not MaxPool2D:
        return None
    extra = dict(getattr(layer, "kw", {}))
    if extra.pop("data_format", data_format) != data_format:
        return None
    if any(extra.values()):
        return None
    if layer._forward_pre_hooks or layer._forward_post_hooks:
        return None
    return ("max", layer.kernel_size,
            layer.stride if layer.stride is not None else
            layer.kernel_size, layer.padding)


def _pool_windows(z, kind, k, s, p, channel_last):
    """Window-reduce z (rank 4) with static slice loops — runs identically
    inside pallas kernels (on a loaded block) and in the jnp reference."""
    kh, kw = k
    sh, sw = s
    ph, pw = p
    hax = 1 if channel_last else 2
    h, w = z.shape[hax], z.shape[hax + 1]
    ho = (h + 2 * ph - kh) // sh + 1
    wo = (w + 2 * pw - kw) // sw + 1
    pads = [(0, 0)] * z.ndim
    pads[hax], pads[hax + 1] = (ph, ph), (pw, pw)
    fill = -jnp.inf if kind == "max" else 0.0
    zp = jnp.pad(z, pads, constant_values=fill)
    cp = jnp.pad(jnp.ones_like(z), pads) if kind == "avg" else None

    def windows(a, di, dj):
        sl = [slice(None)] * z.ndim
        sl[hax] = slice(di, di + (ho - 1) * sh + 1, sh)
        sl[hax + 1] = slice(dj, dj + (wo - 1) * sw + 1, sw)
        return a[tuple(sl)]

    acc = cnt = None
    for di in range(kh):
        for dj in range(kw):
            wz = windows(zp, di, dj)
            if kind == "max":
                acc = wz if acc is None else jnp.maximum(acc, wz)
            else:
                acc = wz if acc is None else acc + wz
                wc = windows(cp, di, dj)
                cnt = wc if cnt is None else cnt + wc
    return acc if kind == "max" else acc / cnt


def _pool_reduce_window(y, kind, k, s, p, channel_last):
    """lax.reduce_window pooling (exclusive avg counting) — the XLA-native
    formulation the reference path uses; the pallas kernel body uses the
    static-slice `_pool_windows` form instead (reduce_window does not
    lower inside Mosaic kernels)."""
    kh, kw = k
    sh, sw = s
    ph, pw = p
    if channel_last:
        window, strides = (1, kh, kw, 1), (1, sh, sw, 1)
        pads = [(0, 0), (ph, ph), (pw, pw), (0, 0)]
    else:
        window, strides = (1, 1, kh, kw), (1, 1, sh, sw)
        pads = [(0, 0), (0, 0), (ph, ph), (pw, pw)]
    if kind == "max":
        return jax.lax.reduce_window(y, -jnp.inf, jax.lax.max, window,
                                     strides, pads)
    s_ = jax.lax.reduce_window(y, 0.0, jax.lax.add, window, strides, pads)
    cnt = jax.lax.reduce_window(jnp.ones_like(y), 0.0, jax.lax.add,
                                window, strides, pads)
    return s_ / cnt


def bn_act_pool_reference(x, gamma, beta, eps, act, pool, channel_axis=-1):
    """(pooled_y, batch_mean, batch_var) — pure jnp oracle, differentiable."""
    kind, k, s, p = _pool_norm(pool)
    y, mean, var = bn_act_reference(x, gamma, beta, eps, act, None,
                                    channel_axis)
    channel_last = channel_axis % x.ndim == x.ndim - 1
    # max pool is exact in the storage dtype; avg accumulates in f32
    pdt = jnp.float32 if (kind == "avg" or y.dtype == jnp.float32) \
        else y.dtype
    yp = _pool_reduce_window(y.astype(pdt), kind, k, s, p, channel_last)
    return yp.astype(x.dtype), mean, var


def _pool_apply_kernel(x_ref, coef_ref, y_ref, *, act, kind, k, s, p):
    zb = x_ref[0].astype(jnp.float32) * coef_ref[0] + coef_ref[1]
    zb = _act_apply(zb, act)
    y_ref[0] = _pool_windows(zb[None], kind, k, s, p,
                             channel_last=True)[0].astype(y_ref.dtype)


# per-image VMEM budget for the pooled kernel (f32 elements of the input
# block; the stem's (112,112,64) is ~0.8M)
_MAX_POOL_BLOCK_ELEMS = 1 << 20


def _run_pool_apply(x4, coef, act, kind, k, s, p):
    n, h, w, c = x4.shape
    ho = (h + 2 * p[0] - k[0]) // s[0] + 1
    wo = (w + 2 * p[1] - k[1]) // s[1] + 1
    return pl.pallas_call(
        functools.partial(_pool_apply_kernel, act=act, kind=kind,
                          k=k, s=s, p=p),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((8, c), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, ho, wo, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, ho, wo, c), x4.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_INTERPRET,
    )(x4, coef)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _bn_pool_p(x, gamma, beta, eps, act, pool, ch):
    out, _ = _bn_pool_fwd(x, gamma, beta, eps, act, pool, ch)
    return out


def _bn_pool_fwd(x, gamma, beta, eps, act, pool, ch):
    kind, k, s, p = _pool_norm(pool)
    c = x.shape[-1]
    m = int(x.size) // c
    blk_m = _block_m(m, c)
    if blk_m is not None:
        sm, sq = _run_stats(x.reshape(m, c), blk_m)
        mean = sm[0] / m
        var = jnp.maximum(sq[0] / m - mean * mean, 0.0)
        invstd = jax.lax.rsqrt(var + eps)
        coef = _coef(mean, invstd, gamma.astype(jnp.float32),
                     beta.astype(jnp.float32))
        yp = _run_pool_apply(x, coef, act, kind, k, s, p)
        return (yp, mean, var), (x, gamma, beta)
    return (bn_act_pool_reference(x, gamma, beta, eps, act, pool, ch),
            (x, gamma, beta))


def _bn_pool_bwd(eps, act, pool, ch, res, cts):
    # recompute backward: re-run the (fused) reference from the saved
    # primals — no full-resolution activations were kept from the forward
    return _ref_vjp(lambda x, g, b: bn_act_pool_reference(
        x, g, b, eps, act, pool, ch), res, cts)


_bn_pool_p.defvjp(_bn_pool_fwd, _bn_pool_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ref_pool_p(x, gamma, beta, eps, act, pool, ch):
    return bn_act_pool_reference(x, gamma, beta, eps, act, pool, ch)


def _ref_pool_fwd(x, gamma, beta, eps, act, pool, ch):
    out = bn_act_pool_reference(x, gamma, beta, eps, act, pool, ch)
    return out, (x, gamma, beta)


def _ref_pool_bwd(eps, act, pool, ch, res, cts):
    return _ref_vjp(lambda x, g, b: bn_act_pool_reference(
        x, g, b, eps, act, pool, ch), res, cts)


_ref_pool_p.defvjp(_ref_pool_fwd, _ref_pool_bwd)


def bn_act_pool_train(x, gamma, beta, eps=1e-5, act=None,
                      pool=("max", 3, 2, 1), channel_last=True):
    """Fused training BatchNorm + activation + 2D max/avg pool.

    x: (N, H, W, C) when channel_last else (N, C, H, W); pool is
    (kind, kernel, stride, padding) with scalar-or-pair ints.  Returns
    (pooled_y, batch_mean_f32, batch_var_f32).

    On TPU (per-image block within VMEM budget) the pallas epilogue
    writes ONLY the pooled output — the normalized full-resolution tensor
    never reaches HBM — and the backward recomputes from the saved input.
    The CPU fallback keeps the same memory discipline through a
    recompute-backward custom_vjp over the reduce_window reference (only
    the primal input crosses the fwd->bwd gap; XLA CSEs the recompute
    with the forward, so bytes accessed do not grow).
    """
    if act not in _ACTS:
        raise ValueError(f"bn_act_pool_train: unsupported activation {act!r}")
    pool = _pool_norm(pool)
    ch = -1 if channel_last else 1
    if _inside_checkpoint():
        return bn_act_pool_reference(x, gamma, beta, eps, act, pool, ch)
    use_kernel = (channel_last and _available() and x.ndim == 4
                  and x.dtype in (jnp.float32, jnp.bfloat16)
                  and int(np.prod(x.shape[1:])) <= _MAX_POOL_BLOCK_ELEMS)
    if use_kernel:
        return _bn_pool_p(x, gamma, beta, float(eps), act, pool, ch)
    return _ref_pool_p(x, gamma, beta, float(eps), act, pool, ch)


# ---------------------------------------------------------------------------
# dual-BN residual: act(bn(x) + bn(res)) as ONE op — the downsample-shortcut
# pattern (ResNet stride blocks).  Both normalizations share the elementwise
# tile the residual add already pays for, so the normalized downsample
# tensor never round-trips HBM on its own.


def bn2_act_reference(x, gamma_x, beta_x, res, gamma_r, beta_r, eps=1e-5,
                      act=None, channel_axis=-1):
    """(y, mean_x, var_x, mean_r, var_r) — pure jnp oracle."""
    ch = channel_axis % x.ndim
    axes = tuple(i for i in range(x.ndim) if i != ch)
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    def affine(v, gamma, beta):
        # f32 stats via single-consumer converts — see bn_act_reference
        mean, var = _ref_stats(v, axes)
        a = (gamma.astype(jnp.float32)
             * jax.lax.rsqrt(var + eps)).reshape(shape)
        b = beta.astype(jnp.float32).reshape(shape) - mean.reshape(shape) * a
        return v.astype(jnp.float32) * a + b, mean, var

    zx, mean_x, var_x = affine(x, gamma_x, beta_x)
    zr, mean_r, var_r = affine(res, gamma_r, beta_r)
    y = _act_apply_ref(zx + zr, act).astype(x.dtype)
    return y, mean_x, var_x, mean_r, var_r


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _bn2_act_p(x2, gamma_x, beta_x, res2, gamma_r, beta_r, eps, act, blk_m):
    (y2, stats), _ = _bn2_fwd_impl(x2, gamma_x, beta_x, res2, gamma_r,
                                   beta_r, eps, act, blk_m)
    return (y2,) + stats


def _bn2_fwd_impl(x2, gamma_x, beta_x, res2, gamma_r, beta_r, eps, act,
                  blk_m):
    m = x2.shape[0]

    def stats_of(v2):
        sm, sq = _run_stats(v2, blk_m)
        mean = sm[0] / m
        var = jnp.maximum(sq[0] / m - mean * mean, 0.0)
        return mean, var, jax.lax.rsqrt(var + eps)

    mean_x, var_x, inv_x = stats_of(x2)
    mean_r, var_r, inv_r = stats_of(res2)
    coef_x = _coef(mean_x, inv_x, gamma_x.astype(jnp.float32),
                   beta_x.astype(jnp.float32))
    coef_r = _coef(mean_r, inv_r, gamma_r.astype(jnp.float32),
                   beta_r.astype(jnp.float32))
    y2 = _run_apply(x2, coef_x, res2, act, blk_m, coefr=coef_r)
    return ((y2, (mean_x, var_x, mean_r, var_r)),
            (mean_x, inv_x, mean_r, inv_r))


def _bn2_act_fwd(x2, gamma_x, beta_x, res2, gamma_r, beta_r, eps, act,
                 blk_m):
    (y2, stats), invs = _bn2_fwd_impl(x2, gamma_x, beta_x, res2, gamma_r,
                                      beta_r, eps, act, blk_m)
    return (y2,) + stats, (x2, gamma_x, beta_x, res2, gamma_r, beta_r, invs)


def _dx_coef(c1, sgz, sgzx, invstd, mean, gmean, gvar, m):
    k = -c1 * sgzx * invstd / m + 2.0 * gvar.astype(jnp.float32) / m
    A = c1
    B = -c1 * sgz / m + gmean.astype(jnp.float32) / m - k * mean
    return A, B, k


def _bn2_act_bwd(eps, act, blk_m, residuals, cts):
    x2, gamma_x, beta_x, res2, gamma_r, beta_r, invs = residuals
    mean_x, inv_x, mean_r, inv_r = invs
    gy, gmx, gvx, gmr, gvr = cts
    m = x2.shape[0]
    gxf = gamma_x.astype(jnp.float32)
    grf = gamma_r.astype(jnp.float32)
    coef_x = _coef(mean_x, inv_x, gxf, beta_x)
    coef_r = _coef(mean_r, inv_r, grf, beta_r)
    sgz, sgzx, sgzr = _run_bwd_reduce(gy, x2, coef_x, res2, act, blk_m,
                                      coefr=coef_r)
    sgz, sgzx, sgzr = sgz[0], sgzx[0], sgzr[0]
    Ax, Bx, Cx = _dx_coef(gxf * inv_x, sgz, sgzx, inv_x, mean_x, gmx, gvx, m)
    Ar, Br, Cr = _dx_coef(grf * inv_r, sgz, sgzr, inv_r, mean_r, gmr, gvr, m)
    coef_dx = _coef(mean_x, inv_x, gxf, beta_x, Ax, Bx, Cx)
    coef_dr = _coef(mean_r, inv_r, grf, beta_r, Ar, Br, Cr)
    dx2, dres2 = _run_bwd_dx(gy, x2, coef_dx, res2, act, blk_m,
                             coefr=coef_dr)
    return (dx2, sgzx.astype(gamma_x.dtype), sgz.astype(beta_x.dtype),
            dres2, sgzr.astype(gamma_r.dtype), sgz.astype(beta_r.dtype))


_bn2_act_p.defvjp(_bn2_act_fwd, _bn2_act_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _ref2_p(x, gamma_x, beta_x, res, gamma_r, beta_r, eps, act, ch):
    return bn2_act_reference(x, gamma_x, beta_x, res, gamma_r, beta_r,
                             eps, act, ch)


def _ref2_fwd(x, gamma_x, beta_x, res, gamma_r, beta_r, eps, act, ch):
    out = bn2_act_reference(x, gamma_x, beta_x, res, gamma_r, beta_r,
                            eps, act, ch)
    return out, (x, gamma_x, beta_x, res, gamma_r, beta_r)


def _ref2_bwd(eps, act, ch, res, cts):
    return _ref_vjp(lambda x, gx, bx, r, gr, br: bn2_act_reference(
        x, gx, bx, r, gr, br, eps, act, ch), res, cts)


_ref2_p.defvjp(_ref2_fwd, _ref2_bwd)


def bn2_act_train(x, gamma_x, beta_x, res, gamma_r, beta_r, eps=1e-5,
                  act=None, channel_last=True):
    """Fused dual training-BN + add + activation: act(bn(x) + bn(res)).

    Both inputs share shape; each has its own (C,) gamma/beta and gets its
    own batch stats back.  Returns (y, mean_x, var_x, mean_r, var_r).
    pallas kernel pair on TPU, recompute-backward jnp reference elsewhere.
    """
    if act not in _ACTS:
        raise ValueError(f"bn2_act_train: unsupported activation {act!r}")
    if res.shape != x.shape:
        raise ValueError("bn2_act_train: residual shape must match x "
                         f"({res.shape} vs {x.shape})")
    ch = -1 if channel_last else 1
    if _inside_checkpoint():
        return bn2_act_reference(x, gamma_x, beta_x, res, gamma_r, beta_r,
                                 eps, act, ch)
    use_kernel = (channel_last and _available() and x.ndim >= 2
                  and x.dtype in (jnp.float32, jnp.bfloat16))
    if use_kernel:
        c = x.shape[-1]
        m = int(x.size) // c
        blk_m = _block_m(m, c)
        if blk_m is not None:
            y2, mean_x, var_x, mean_r, var_r = _bn2_act_p(
                x.reshape(m, c), gamma_x, beta_x,
                res.astype(x.dtype).reshape(m, c), gamma_r, beta_r,
                float(eps), act, blk_m)
            return y2.reshape(x.shape), mean_x, var_x, mean_r, var_r
    return _ref2_p(x, gamma_x, beta_x, res.astype(x.dtype), gamma_r,
                   beta_r, float(eps), act, ch)
