"""Fused training-BatchNorm + activation + residual-add for TPU via pallas.

The r5 ResNet-50 bench decomposition (bench.py RESNET notes) showed the
step is bound not by conv rate but by the BN/elementwise HBM traffic:
~8 passes over 5.7 GB of bf16 activations (conv write, stats read,
normalize+relu write, next-conv read, plus the backward re-reads)
~= 55 ms of a 118 ms step.  XLA cannot fuse a training-mode BN chain
below its reduce/elementwise granularity, so this module does it by
hand (design notes: /opt/skills/guides/pallas_guide.md):

- forward = 2 HBM passes: one single-pass stats kernel (sum and
  sum-of-squares accumulated together, f32, per channel) + one apply
  kernel computing `act(x * a + b [+ residual])` with the per-channel
  affine folded on the host side of the trace;
- backward = 2 passes with a `custom_vjp` that RECOMPUTES x_hat and the
  activation mask from the saved input instead of re-reading saved
  normalized/pre-activation tensors: one reduce kernel for the
  d_gamma/d_beta sums, one elementwise kernel producing dx (and the
  residual gradient) from three per-channel coefficients;
- all per-channel math ((C,)-sized) runs as plain traced jnp — it is
  nanoseconds and keeps the kernels pure elementwise/reduce.

Data is handled channels-last as a free (M, C) = (N*H*W, C) reshape —
the layout `core.layout` puts conv-net activations in anyway.  On
non-TPU backends (tier-1 CI runs `JAX_PLATFORMS=cpu`) `bn_act_train`
automatically selects a pure-jnp reference with identical semantics;
tests flip `_INTERPRET` to run the kernels through the pallas
interpreter and check parity against that reference.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_INTERPRET = False  # tests flip this to run the kernels via the interpreter

from ._compat import CompilerParams as _CompilerParams

_ACTS = (None, "relu", "relu6")
# VMEM budget per (blk_m, C) block: keep each f32 buffer <= ~512 KB so the
# worst kernel (bwd dx: g, x, res in + dx, dres out) stays well under VMEM
_MAX_BLOCK_ELEMS = 1 << 17


def _available() -> bool:
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


def _block_m(m: int, c: int):
    """Largest divisor of m that is a multiple of 8 and fits the VMEM
    budget; None when m has no usable divisor (jnp fallback)."""
    cap = min(m, max(8, _MAX_BLOCK_ELEMS // max(c, 1)))
    cap -= cap % 8
    for blk in range(cap, 7, -8):
        if m % blk == 0:
            return blk
    return None


def _act_apply(z, act):
    if act == "relu":
        return jnp.maximum(z, 0.0)
    if act == "relu6":
        return jnp.clip(z, 0.0, 6.0)
    return z


def _act_mask(z, act):
    if act == "relu":
        return z > 0.0
    if act == "relu6":
        return jnp.logical_and(z > 0.0, z < 6.0)
    return None


# ---------------------------------------------------------------------------
# pallas kernels.  x is viewed as (M, C); the grid walks M in blk_m rows.
# Per-channel vectors ride in one (8, C) f32 `coef` input:
#   row 0 = a  (gamma * invstd)        row 1 = b  (beta - mean * a)
#   row 2 = mean                       row 3 = invstd
#   row 4 = A, row 5 = B, row 6 = Cc   (backward dx coefficients)


def _stats_kernel(x_ref, sum_ref, sq_ref, *, n_m):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    xb = x_ref[...].astype(jnp.float32)
    sum_ref[...] += jnp.sum(xb, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(xb * xb, axis=0, keepdims=True)


def _apply_kernel(*refs, act, has_res):
    it = iter(refs)
    x_ref, coef_ref = next(it), next(it)
    res_ref = next(it) if has_res else None
    y_ref = next(it)
    z = x_ref[...].astype(jnp.float32) * coef_ref[0:1] + coef_ref[1:2]
    if has_res:
        z = z + res_ref[...].astype(jnp.float32)
    y_ref[...] = _act_apply(z, act).astype(y_ref.dtype)


def _bwd_reduce_kernel(*refs, act, has_res):
    it = iter(refs)
    g_ref, x_ref, coef_ref = next(it), next(it), next(it)
    res_ref = next(it) if has_res else None
    sgz_ref, sgzx_ref = next(it), next(it)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sgz_ref[...] = jnp.zeros_like(sgz_ref)
        sgzx_ref[...] = jnp.zeros_like(sgzx_ref)

    xb = x_ref[...].astype(jnp.float32)
    gz = g_ref[...].astype(jnp.float32)
    if act is not None:
        z = xb * coef_ref[0:1] + coef_ref[1:2]
        if has_res:
            z = z + res_ref[...].astype(jnp.float32)
        gz = jnp.where(_act_mask(z, act), gz, 0.0)
    xhat = (xb - coef_ref[2:3]) * coef_ref[3:4]
    sgz_ref[...] += jnp.sum(gz, axis=0, keepdims=True)
    sgzx_ref[...] += jnp.sum(gz * xhat, axis=0, keepdims=True)


def _bwd_dx_kernel(*refs, act, has_res):
    it = iter(refs)
    g_ref, x_ref, coef_ref = next(it), next(it), next(it)
    res_ref = next(it) if has_res else None
    dx_ref = next(it)
    dres_ref = next(it) if has_res else None
    xb = x_ref[...].astype(jnp.float32)
    gz = g_ref[...].astype(jnp.float32)
    if act is not None:
        z = xb * coef_ref[0:1] + coef_ref[1:2]
        if has_res:
            z = z + res_ref[...].astype(jnp.float32)
        gz = jnp.where(_act_mask(z, act), gz, 0.0)
    dx = coef_ref[4:5] * gz + coef_ref[5:6] + coef_ref[6:7] * xb
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if has_res:
        dres_ref[...] = gz.astype(dres_ref.dtype)


def _row_spec(blk_m, c):
    return pl.BlockSpec((blk_m, c), lambda i: (i, 0))


def _const_spec(rows, c):
    return pl.BlockSpec((rows, c), lambda i: (0, 0))


def _coef(mean, invstd, gamma, beta, A=None, B=None, Cc=None):
    c = mean.shape[0]
    a = gamma * invstd
    b = beta - mean * a
    zero = jnp.zeros((c,), jnp.float32)
    rows = [a, b, mean, invstd, A if A is not None else zero,
            B if B is not None else zero, Cc if Cc is not None else zero,
            zero]
    return jnp.stack([r.astype(jnp.float32) for r in rows])


def _run_stats(x2, blk_m):
    m, c = x2.shape
    n_m = m // blk_m
    return pl.pallas_call(
        functools.partial(_stats_kernel, n_m=n_m),
        grid=(n_m,),
        in_specs=[_row_spec(blk_m, c)],
        out_specs=[_const_spec(1, c), _const_spec(1, c)],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(x2)


def _run_apply(x2, coef, res2, act, blk_m):
    m, c = x2.shape
    inputs = [x2, coef] + ([res2] if res2 is not None else [])
    in_specs = [_row_spec(blk_m, c), _const_spec(8, c)] + \
        ([_row_spec(blk_m, c)] if res2 is not None else [])
    return pl.pallas_call(
        functools.partial(_apply_kernel, act=act, has_res=res2 is not None),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=_row_spec(blk_m, c),
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_INTERPRET,
    )(*inputs)


def _run_bwd_reduce(g2, x2, coef, res2, act, blk_m):
    m, c = x2.shape
    inputs = [g2, x2, coef] + ([res2] if res2 is not None else [])
    in_specs = [_row_spec(blk_m, c), _row_spec(blk_m, c),
                _const_spec(8, c)] + \
        ([_row_spec(blk_m, c)] if res2 is not None else [])
    return pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, act=act,
                          has_res=res2 is not None),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=[_const_spec(1, c), _const_spec(1, c)],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_INTERPRET,
    )(*inputs)


def _run_bwd_dx(g2, x2, coef, res2, act, blk_m):
    m, c = x2.shape
    has_res = res2 is not None
    inputs = [g2, x2, coef] + ([res2] if has_res else [])
    in_specs = [_row_spec(blk_m, c), _row_spec(blk_m, c),
                _const_spec(8, c)] + ([_row_spec(blk_m, c)] if has_res else [])
    out_specs = [_row_spec(blk_m, c)] + ([_row_spec(blk_m, c)] if has_res
                                         else [])
    out_shape = [jax.ShapeDtypeStruct((m, c), x2.dtype)] + \
        ([jax.ShapeDtypeStruct((m, c), res2.dtype)] if has_res else [])
    outs = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, act=act, has_res=has_res),
        grid=(m // blk_m,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_INTERPRET,
    )(*inputs)
    return (outs[0], outs[1]) if has_res else (outs[0], None)


# ---------------------------------------------------------------------------
# custom_vjp over the (M, C) view


def _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m):
    m = x2.shape[0]
    s, sq = _run_stats(x2, blk_m)
    mean = s[0] / m
    var = jnp.maximum(sq[0] / m - mean * mean, 0.0)
    invstd = jax.lax.rsqrt(var + eps)
    coef = _coef(mean, invstd, gamma, beta)
    y2 = _run_apply(x2, coef, res2, act, blk_m)
    return y2, mean, var, invstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bn_act_p(x2, gamma, beta, res2, eps, act, blk_m):
    y2, mean, var, _ = _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m)
    return y2, mean, var


def _bn_act_fwd(x2, gamma, beta, res2, eps, act, blk_m):
    y2, mean, var, invstd = _fwd_impl(x2, gamma, beta, res2, eps, act, blk_m)
    return (y2, mean, var), (x2, gamma, beta, res2, mean, invstd)


def _bn_act_bwd(eps, act, blk_m, residuals, cts):
    x2, gamma, beta, res2, mean, invstd = residuals
    gy, gmean, gvar = cts
    m = x2.shape[0]
    gammaf = gamma.astype(jnp.float32)
    coef = _coef(mean, invstd, gammaf, beta)
    sgz, sgzx = _run_bwd_reduce(gy, x2, coef, res2, act, blk_m)
    sgz, sgzx = sgz[0], sgzx[0]
    # dx = c1*(gz - sgz/M - xhat*sgzx/M) + gmean/M + gvar*2*(x-mean)/M
    #    = A*gz + B + Cc*x   with the xhat/mean terms folded per channel
    c1 = gammaf * invstd
    k = -c1 * sgzx * invstd / m + 2.0 * gvar.astype(jnp.float32) / m
    A = c1
    B = -c1 * sgz / m + gmean.astype(jnp.float32) / m - k * mean
    Cc = k
    coef_dx = _coef(mean, invstd, gammaf, beta, A, B, Cc)
    dx2, dres2 = _run_bwd_dx(gy, x2, coef_dx, res2, act, blk_m)
    dgamma = sgzx.astype(gamma.dtype)
    dbeta = sgz.astype(beta.dtype)
    return dx2, dgamma, dbeta, dres2


_bn_act_p.defvjp(_bn_act_fwd, _bn_act_bwd)


# ---------------------------------------------------------------------------
# reference (pure jnp): same math, any channel axis, fully differentiable.
# Used on CPU / whenever the kernels don't apply, and as the test oracle.


def bn_act_reference(x, gamma, beta, eps=1e-5, act=None, residual=None,
                     channel_axis=-1):
    """Returns (y, batch_mean, batch_var) — f32 stats, biased variance."""
    ch = channel_axis % x.ndim
    axes = tuple(i for i in range(x.ndim) if i != ch)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
    shape = [1] * x.ndim
    shape[ch] = x.shape[ch]
    a = (gamma.astype(jnp.float32) * jax.lax.rsqrt(var + eps)).reshape(shape)
    b = (beta.astype(jnp.float32)).reshape(shape) - mean.reshape(shape) * a
    z = xf * a + b
    if residual is not None:
        z = z + residual.astype(jnp.float32)
    return _act_apply(z, act).astype(x.dtype), mean, var


# ---------------------------------------------------------------------------
# public entry


def bn_act_train(x, gamma, beta, eps=1e-5, act=None, residual=None,
                 channel_last=True):
    """Fused training BatchNorm + optional residual-add + activation.

    x: (N, ..., C) when channel_last else (N, C, ...); gamma/beta: (C,);
    residual: same shape as x or None; act in {None, "relu", "relu6"}.
    Returns (y, batch_mean_f32, batch_var_f32).  Selects the pallas
    kernel pair on TPU (or under `_INTERPRET`), the jnp reference
    otherwise — callers never need to know which ran.
    """
    if act not in _ACTS:
        raise ValueError(f"bn_act_train: unsupported activation {act!r}")
    ch = -1 if channel_last else 1
    use_kernel = (channel_last and _available() and x.ndim >= 2
                  and x.dtype in (jnp.float32, jnp.bfloat16)
                  and (residual is None or residual.shape == x.shape))
    if use_kernel:
        c = x.shape[-1]
        m = int(x.size) // c
        blk_m = _block_m(m, c)
        if blk_m is not None:
            x2 = x.reshape(m, c)
            res2 = None if residual is None else \
                residual.astype(x.dtype).reshape(m, c)
            y2, mean, var = _bn_act_p(x2, gamma, beta, res2, float(eps),
                                      act, blk_m)
            return y2.reshape(x.shape), mean, var
    return bn_act_reference(x, gamma, beta, eps, act, residual, ch)
