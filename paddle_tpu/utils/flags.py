"""Global flag registry.

Reference: C++ gflags (platform/flags.cc, 27 defs) exported to Python via
global_value_getter_setter.cc and FLAGS_* env bootstrap
(python/paddle/fluid/__init__.py:143).  TPU-native: a plain registry +
env-var bootstrap; XLA/jax config knobs are mapped where meaningful.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_FLAGS: Dict[str, Any] = {
    # numerics / debugging (reference flags.cc:44 FLAGS_check_nan_inf)
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,   # no-op: XLA manages memory
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_cpu_deterministic": False,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_use_pinned_memory": True,
    "FLAGS_paddle_num_threads": 1,
    # tpu-specific additions
    "FLAGS_use_flash_attention": True,
    "FLAGS_amp_dtype": "bfloat16",
    "FLAGS_allocator_strategy": "xla",
    # monitor (reference platform/monitor.h STAT registry)
    "FLAGS_reset_stats": False,
}


def _apply_effect(key: str, value):
    """Push a flag's live effect into its consumer."""
    if key == "FLAGS_use_flash_attention":
        from ..nn.functional.attention import set_flash_attention
        set_flash_attention(bool(value))
    elif key == "FLAGS_check_nan_inf":
        from ..core.op import set_check_nan_inf
        set_check_nan_inf(bool(value))
    elif key == "FLAGS_reset_stats" and value:
        # a live reset clears the observability registry the STAT shim
        # writes into (values zeroed, registrations + collectors survive),
        # not just the legacy STAT name set
        from .monitor import stat_reset
        stat_reset()
        from ..observability import get_registry
        get_registry().reset()


def _bootstrap_from_env():
    for key in list(_FLAGS):
        env = os.environ.get(key)
        if env is not None:
            cur = _FLAGS[key]
            if isinstance(cur, bool):
                _FLAGS[key] = env.lower() in ("1", "true", "yes")
            elif isinstance(cur, int):
                _FLAGS[key] = int(env)
            elif isinstance(cur, float):
                _FLAGS[key] = float(env)
            else:
                _FLAGS[key] = env
            _apply_effect(key, _FLAGS[key])


_bootstrap_from_env()


def get_flags(flags):
    if isinstance(flags, str):
        return {flags: _FLAGS.get(flags)}
    return {f: _FLAGS.get(f) for f in flags}


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        _FLAGS[k] = v
        _apply_effect(k, v)


def get_flag(name, default=None):
    return _FLAGS.get(name, default)
