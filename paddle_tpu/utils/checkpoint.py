"""Checkpoint save/load.

Reference: paddle.save/load (fluid/dygraph/checkpoint.py), save ops
(operators/save_combine_op.cc), auto-checkpoint
(fluid/incubate/checkpoint/auto_checkpoint.py).  TPU-native: state dicts of
jax arrays serialized either via pickle-of-numpy (paddle-compatible API) or
orbax for sharded async checkpoints of distributed runs (see
paddle_tpu.distributed.checkpoint).
"""
from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor


def _to_numpy_tree(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_numpy_tree(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    if hasattr(obj, "dtype") and hasattr(obj, "shape"):
        return np.asarray(obj)
    return obj


def _to_tensor_tree(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(jnp.asarray(obj))
    if isinstance(obj, dict):
        return {k: _to_tensor_tree(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_to_tensor_tree(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, encrypt_key=None):
    """paddle.save: state_dict / nested structure -> file.

    encrypt_key: optional AES key (16/24/32 bytes) — artifact is written
    through the native cipher (reference framework/io/crypto/)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = pickle.dumps(_to_numpy_tree(obj), protocol=protocol)
    if encrypt_key is not None:
        from ..io.crypto import AESCipher
        AESCipher().encrypt_to_file(payload, encrypt_key, path)
        return
    with open(path, "wb") as f:
        f.write(payload)


def load(path, return_numpy=False, encrypt_key=None, allow_legacy=False,
         **kwargs):
    """paddle.load.  allow_legacy opts in to v1 (unauthenticated) encrypted
    artifacts — see io/crypto.py on the downgrade hazard."""
    import os
    if not os.path.exists(path):
        from ..core.errors import NotFoundError
        raise NotFoundError(
            f"[NotFound] paddle.load: no artifact at {path!r}")
    if encrypt_key is not None:
        from ..io.crypto import AESCipher
        payload = AESCipher().decrypt_from_file(encrypt_key, path,
                                                allow_legacy=allow_legacy)
        obj = pickle.loads(payload)
    else:
        with open(path, "rb") as f:
            obj = pickle.load(f)
    if return_numpy:
        return obj
    return _to_tensor_tree(obj)
