"""Monitor: cheap always-on global STAT counters — compat shim.

Reference: paddle/fluid/platform/monitor.h:77 (StatRegistry,
STAT_ADD/STAT_SUB/STAT_RESET macros backing e.g. the dataset-feed byte/ins
counters in data_feed.cc) and monitor.h:130 (the int64 stat registration
list).

Since PR 5 the STAT values live in `paddle_tpu.observability`'s typed
metrics registry (as Gauges — the STAT verbs go both ways, stat_sub is
real usage) instead of a private dict: the same names show up in
`observability.report()`, the Prometheus endpoint and `stats()` here.
The verbs keep their exact legacy semantics; `stats()` gains prefix
filtering and `FLAGS_reset_stats` (utils.flags) clears the registry-backed
values, not a shadow dict.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..observability.metrics import get_registry

__all__ = ["stat_add", "stat_sub", "stat_get", "stat_reset", "stats",
           "STAT_ADD", "STAT_SUB", "STAT_RESET"]

# names created through the STAT verbs: stats() reports exactly these (the
# registry also holds non-STAT metrics that must not leak into the legacy
# view)
_names_lock = threading.Lock()
_names: set = set()
# handle memo: hot call sites (dataloader per batch, serving per token
# burst) pay one dict hit instead of registry get-or-create locks per
# bump; invalidated by stat_reset (which removes the gauges)
_gauge_memo: Dict[str, object] = {}


def _gauge(name: str):
    g = _gauge_memo.get(name)
    if g is None:
        g = get_registry().gauge(
            name, help="legacy STAT counter (utils.monitor shim)")
        with _names_lock:
            _names.add(name)
            _gauge_memo[name] = g
    return g


def stat_add(name: str, value: int = 1) -> None:
    _gauge(name).inc(int(value))


def stat_sub(name: str, value: int = 1) -> None:
    stat_add(name, -int(value))


def stat_get(name: str) -> int:
    m = get_registry().get(name)
    if m is None:
        return 0
    try:
        return int(m.value())
    except Exception:
        return 0


def stat_reset(name: Optional[str] = None) -> None:
    reg = get_registry()
    with _names_lock:
        if name is None:
            targets = sorted(_names)
            _names.clear()
            _gauge_memo.clear()
        else:
            targets = [name] if name in _names else []
            _names.discard(name)
            _gauge_memo.pop(name, None)
    for n in targets:
        reg.remove(n)


def stats(prefix: Optional[str] = None) -> Dict[str, int]:
    """Snapshot of the STAT counters.  `prefix` filters by name; for
    convenience it matches either the full name or the part after the
    conventional `STAT_` prefix, so `stats(prefix="serving_")` returns the
    `STAT_serving_*` family."""
    with _names_lock:
        names = sorted(_names)
    out = {}
    for n in names:
        if prefix is not None and not (
                n.startswith(prefix)
                or (n.startswith("STAT_")
                    and n[len("STAT_"):].startswith(prefix))):
            continue
        out[n] = stat_get(n)
    return out


# macro-style aliases matching the reference's spelling
STAT_ADD = stat_add
STAT_SUB = stat_sub
STAT_RESET = stat_reset
