"""Monitor: cheap always-on global STAT counters.

Reference: paddle/fluid/platform/monitor.h:77 (StatRegistry,
STAT_ADD/STAT_SUB/STAT_RESET macros backing e.g. the dataset-feed byte/ins
counters in data_feed.cc) and monitor.h:130 (the int64 stat registration
list).  TPU-native: a process-local dict with the same add/sub/get/reset
verbs; the runtime hot paths (dataloader, dataset engine, checkpointing)
bump these, `profiler.summary()` surfaces them next to op spans, and the
`FLAGS_reset_stats` flag clears them live.
"""
from __future__ import annotations

import threading
from typing import Dict

__all__ = ["stat_add", "stat_sub", "stat_get", "stat_reset", "stats",
           "STAT_ADD", "STAT_SUB", "STAT_RESET"]

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> None:
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)


def stat_sub(name: str, value: int = 1) -> None:
    stat_add(name, -int(value))


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_reset(name: str = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


# macro-style aliases matching the reference's spelling
STAT_ADD = stat_add
STAT_SUB = stat_sub
STAT_RESET = stat_reset
