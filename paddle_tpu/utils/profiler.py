"""Profiler.

Reference: platform/profiler.h RecordEvent/EnableProfiler + CUPTI
DeviceTracer -> chrome trace (platform/device_tracer.h).  TPU-native:
jax.profiler (XLA/TensorBoard trace) for the device timeline + a host-side
op-span recorder hooked into core.op dispatch for eager-mode op accounting.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Optional

import jax

from ..core import op as _op

_records = defaultdict(lambda: [0, 0.0])  # name -> [count, total_s]
_events: list = []                        # (name, t0_s, dur_s) for the trace
_MAX_EVENTS = 200_000                     # bound host memory
_enabled = False


class _Span:
    __slots__ = ("name", "t0")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        now = time.perf_counter()
        rec = _records[self.name]
        rec[0] += 1
        rec[1] += now - self.t0
        if len(_events) < _MAX_EVENTS:
            _events.append((self.name, self.t0, now - self.t0))
        return False


def _hook(name):
    return _Span(name)


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """reference: fluid.profiler.start_profiler"""
    global _enabled
    _enabled = True
    _records.clear()
    _events.clear()
    _op.set_profiler_hook(_hook)
    if log_dir:
        jax.profiler.start_trace(log_dir)
        start_profiler._trace_dir = log_dir
    else:
        start_profiler._trace_dir = None


def stop_profiler(sorted_key="total", profile_path=None):
    global _enabled
    _enabled = False
    _op.set_profiler_hook(None)
    if getattr(start_profiler, "_trace_dir", None):
        jax.profiler.stop_trace()
    rows = sorted(_records.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'op':<32}{'calls':>10}{'total_s':>14}{'avg_ms':>12}"]
    for name, (cnt, tot) in rows[:50]:
        lines.append(f"{name:<32}{cnt:>10}{tot:>14.4f}{tot / cnt * 1e3:>12.4f}")
    # dispatch fast-path accounting (the hook fires on hit AND miss paths,
    # so per-op spans above already include both; this line attributes them)
    cs = _op.dispatch_cache_stats()
    lines.append(
        f"dispatch cache: hits={cs['hits']} misses={cs['misses']} "
        f"fallbacks={cs['fallbacks']} bypass={cs['bypass']} "
        f"entries={cs['entries']}/{cs['max_entries']} "
        f"enabled={cs['enabled']}")
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    else:
        print(report)
    return dict(_records)


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None, log_dir=None):
    """`with paddle_tpu.utils.profiler.profiler():` context."""
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """RAII host span (reference: platform/profiler.h:127)."""

    def __init__(self, name):
        self.name = name
        self._span = None
        self._jax_ctx = None

    def __enter__(self):
        self._span = _Span(self.name).__enter__()
        try:
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()
        except Exception:
            self._jax_ctx = None
        return self

    def __exit__(self, *exc):
        if self._jax_ctx is not None:
            self._jax_ctx.__exit__(*exc)
        self._span.__exit__(*exc)
        return False

    def end(self):
        self.__exit__(None, None, None)


def summary():
    """Op-span records plus the monitor's STAT counters (reference:
    platform/monitor.h StatRegistry — surfaced here the way the reference
    prints stats alongside the profiler report)."""
    out = dict(_records)
    from .monitor import stats
    st = stats()
    if st:
        out["__stats__"] = st
    return out


def export_chrome_tracing(path: str) -> str:
    """Write recorded host op spans as a chrome://tracing (catapult) JSON —
    the analogue of the reference DeviceTracer's GenProfile chrome trace
    (platform/device_tracer.cc).  The XLA device timeline comes from the
    jax.profiler trace dir (TensorBoard); this file covers the host/eager
    dispatch side."""
    import json
    import os
    events = [{
        "name": name, "ph": "X", "cat": "op",
        "ts": t0 * 1e6, "dur": dur * 1e6,
        "pid": 0, "tid": 0,
    } for name, t0, dur in _events]
    doc = {"traceEvents": events, "displayTimeUnit": "ms"}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
