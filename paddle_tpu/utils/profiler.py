"""Profiler — compat shim over `paddle_tpu.observability.tracer`.

Reference: platform/profiler.h RecordEvent/EnableProfiler + CUPTI
DeviceTracer -> chrome trace (platform/device_tracer.h).  TPU-native:
jax.profiler (XLA/TensorBoard trace) for the device timeline + host spans
for eager-mode op accounting.

Since PR 5 the span storage is the observability tracer (bounded ring +
per-name aggregates under a lock) instead of this module's bare
`_records` dict / `_events` list — which serving-engine threads used to
mutate concurrently without a lock.  The public API (start_profiler /
stop_profiler / profiler / RecordEvent / summary / export_chrome_tracing)
is unchanged, and `_records` / `_events` remain readable as snapshots for
callers that poked the internals.
"""
from __future__ import annotations

import contextlib

import jax

from ..core import op as _op
from ..observability.tracer import get_tracer

_enabled = False
# aggregates snapshot taken at start_profiler: the profiler reports the
# DELTA since then, so starting a profile no longer wipes span history
# other subsystems (checkpoint writer, train loop, serving engine)
# accumulated in the shared tracer
_baseline: dict = {}


def _hook(name):
    # light span: the hook fires on EVERY eager dispatch — pay wall-time +
    # ring/aggregate recording only (no ids/parenting/annotation)
    return get_tracer().light_span(name)


def _delta():
    agg = get_tracer().aggregates()
    out = {}
    for k, (c, t) in agg.items():
        bc, bt = _baseline.get(k, (0, 0.0))
        if c - bc > 0:
            out[k] = [c - bc, t - bt]
    return out


def __getattr__(name):
    # legacy internals, now lock-safe snapshots of the tracer state
    if name == "_records":
        return _delta()
    if name == "_events":
        return [(n, t0, dur) for n, t0, dur, *_ in get_tracer().events()]
    raise AttributeError(name)


def start_profiler(state="All", tracer_option="Default", log_dir=None):
    """reference: fluid.profiler.start_profiler"""
    global _enabled, _baseline
    _enabled = True
    _baseline = get_tracer().aggregates()
    _op.set_profiler_hook(_hook)
    if log_dir:
        jax.profiler.start_trace(log_dir)
        start_profiler._trace_dir = log_dir
    else:
        start_profiler._trace_dir = None


def stop_profiler(sorted_key="total", profile_path=None):
    global _enabled
    _enabled = False
    _op.set_profiler_hook(None)
    if getattr(start_profiler, "_trace_dir", None):
        jax.profiler.stop_trace()
    agg = _delta()
    rows = sorted(agg.items(), key=lambda kv: -kv[1][1])
    lines = [f"{'op':<32}{'calls':>10}{'total_s':>14}{'avg_ms':>12}"]
    for name, (cnt, tot) in rows[:50]:
        lines.append(f"{name:<32}{cnt:>10}{tot:>14.4f}{tot / cnt * 1e3:>12.4f}")
    # dispatch fast-path accounting (the hook fires on hit AND miss paths,
    # so per-op spans above already include both; this line attributes them)
    cs = _op.dispatch_cache_stats()
    lines.append(
        f"dispatch cache: hits={cs['hits']} misses={cs['misses']} "
        f"fallbacks={cs['fallbacks']} bypass={cs['bypass']} "
        f"entries={cs['entries']}/{cs['max_entries']} "
        f"enabled={cs['enabled']}")
    report = "\n".join(lines)
    if profile_path:
        with open(profile_path, "w") as f:
            f.write(report)
    else:
        print(report)
    return agg


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None, log_dir=None):
    """`with paddle_tpu.utils.profiler.profiler():` context."""
    start_profiler(state, log_dir=log_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class RecordEvent:
    """RAII host span (reference: platform/profiler.h:127) — an
    observability span with the jax TraceAnnotation passthrough, so host
    spans line up with the XLA device timeline."""

    def __init__(self, name):
        self.name = name
        self._span = None

    def __enter__(self):
        self._span = get_tracer().span(self.name, annotate=True)
        return self

    def __exit__(self, *exc):
        if self._span is not None:
            self._span.end()
            self._span = None
        return False

    def end(self):
        self.__exit__(None, None, None)


def summary():
    """Op-span records plus the monitor's STAT counters (reference:
    platform/monitor.h StatRegistry — surfaced here the way the reference
    prints stats alongside the profiler report)."""
    out = _delta()  # == full aggregates when no profile was ever started
    from .monitor import stats
    st = stats()
    if st:
        out["__stats__"] = st
    return out


def export_chrome_tracing(path: str) -> str:
    """Write recorded host spans as a chrome://tracing (catapult) JSON —
    the analogue of the reference DeviceTracer's GenProfile chrome trace
    (platform/device_tracer.cc).  The XLA device timeline comes from the
    jax.profiler trace dir (TensorBoard); this file covers the host side.
    Spans carry real thread ids + parent links now (observability
    tracer)."""
    return get_tracer().export_chrome_trace(path)
