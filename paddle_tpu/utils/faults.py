"""Fault injection for resilience testing.

Reference: the reference framework's fault tolerance (EDL preemption,
checkpoint_notify, reader worker restarts) ships with no way to *prove* the
recovery paths work — they are only exercised by real production failures.
This module gives every recovery path in paddle_tpu a deterministic trigger,
driven by env vars (so subprocess / worker-process faults inherit them) or
the in-process API, and the `faults`-marked test suite + resilience probe
use it to demonstrate end-to-end recovery in CI.

Injection points (consumed elsewhere in the framework):

  nan_grads       step k (or [a, b) range): compiled train steps poison the
                  gradients with NaN when step_no hits the window.  The
                  *presence* of the injection is decided at trace time, so
                  the production compiled step carries zero overhead.
                  Env: PDTPU_FAULT_NAN_GRADS="k" or "a:b".
  worker_crash    DataLoader worker hard-exits (mode "kill", exercising the
                  death-detect + respawn path) or raises (mode "exc",
                  exercising error propagation) when it picks up batch seq
                  S.  A `once` sentinel file makes the fault fire a single
                  time so the respawned worker can finish the batch.
                  Env: PDTPU_FAULT_WORKER_CRASH="kill:S[:/path/once]".
  kill_mid_save   checkpoint writer SIGKILLs its own process right before
                  the atomic rename of save number N (1-based), proving a
                  kill mid-save never corrupts the latest checkpoint.
                  Env: PDTPU_FAULT_KILL_MID_SAVE="N".
  backend_down    the bench backend probe reports the accelerator tunnel
                  unreachable without waiting out a real timeout.
                  Env: PDTPU_FAULT_BACKEND_DOWN="1".
  prefetch_stall  the host-embedding-table prefetch worker sleeps `ms`
                  milliseconds before every `every_n`-th row fetch
                  (default every fetch) — a slow host memory system /
                  storage tier.  Purely host-side and consulted live per
                  fetch, so it can be armed on a running pipeline.  The
                  async prefetch pipeline must degrade to synchronous-
                  fetch throughput (the consumer waits; prefetch misses
                  climb) WITHOUT changing any training result.
                  Env: PDTPU_FAULT_PREFETCH_STALL="ms[:every_n]".
  row_corrupt     poison ONE row (NaN) of the N-th (1-based) fetched row
                  slab AFTER it leaves the host table — a torn DMA /
                  bit-flipped transfer.  The pipeline's consume-side
                  finiteness verify must detect the poisoned copy and
                  refetch from the host table (the source of truth is
                  untouched), so training stays bit-identical to a clean
                  run.  Env: PDTPU_FAULT_ROW_CORRUPT="N".
  nan_logits      the serving engine's compiled decode step poisons the
                  logits of the request with submission sequence number N
                  (0-based) with NaN, exercising the engine's per-slot
                  non-finite guard: the poisoned request must error and
                  free its slot while the other slots keep decoding.  The
                  *presence* of the injection is decided at decode TRACE
                  time (engine construction), so the production decode
                  program carries zero overhead; which slot is poisoned is
                  a dynamic input.  Env: PDTPU_FAULT_NAN_LOGITS="N".
  draft_diverge   the speculative-decoding verify program poisons the
                  DRAFT model's logits (negation: the draft proposes its
                  least-likely token) on every N-th speculative tick,
                  driving the accept rate toward zero.  Proves the
                  accept/reject path degrades gracefully to target-only
                  throughput: streams stay bit-identical (greedy) /
                  distribution-preserving (sampling) because rejected
                  proposals never commit — only tokens/sec drops.  The
                  *presence* of the injection is decided at verify TRACE
                  time (engine construction); whether the current tick
                  diverges is a dynamic input.
                  Env: PDTPU_FAULT_DRAFT_DIVERGE="N".
  kv_exhaust      the paged KV-cache block allocator pretends the pool
                  only holds N blocks (capacity capped live, host-side —
                  nothing is baked into any trace), forcing the
                  exhaustion paths on CPU without a big pool: admission
                  backpressure, mid-decode preemption of the newest
                  low-priority run, and the typed KVPoolExhaustedError
                  terminal state.  Arm/disarm takes effect on the next
                  allocator call.  Env: PDTPU_FAULT_KV_EXHAUST="N".
  prefix_evict    the serving prefix cache caps the number of RESIDENT
                  refcount-0 cached blocks at N (consulted live on every
                  release/insert, host-side only — nothing is baked into
                  any trace), forcing LRU eviction and copy-on-write
                  churn on CPU without filling a real pool.  N=0 means
                  nothing stays cached after its last reference drops —
                  every warm request becomes a cold one.
                  Env: PDTPU_FAULT_PREFIX_EVICT="N".
  slow_decode     the serving engine sleeps `ms` milliseconds on the host
                  before every `every_n`-th decode call (default every
                  call).  Purely host-side — the compiled decode program
                  is untouched and the injection is consulted live per
                  call, so it can be armed/disarmed on a running engine.
                  Makes overload, SLO-miss, and mid-decode-deadline paths
                  testable on CPU without a big model.
                  Env: PDTPU_FAULT_SLOW_DECODE="ms[:every_n]".
  replica_crash   the fleet replica with index `replica` dies abruptly at
                  its `tick`-th step (0-based) — the SIGKILL-equivalent
                  for in-process replicas: the step raises mid-loop, the
                  engine gets no chance to fail its runs, and the
                  ReplicaManager must fence the replica and fail over
                  every resident stream (resubmit or typed terminal;
                  never a hang).  Live-read per replica step, like
                  slow_decode.  Env: PDTPU_FAULT_REPLICA_CRASH=
                  "replica:tick".
  replica_slow    a fleet replica's step loop sleeps `ms` milliseconds on
                  the host before every `every_n`-th step — the brownout:
                  a browned-out replica serves, just far too slowly, and
                  the ReplicaManager's step-time health tracking must
                  fence it and migrate its residents to fast replicas.
                  The optional third field targets one replica index
                  (default: every replica).  Live-read per step, nothing
                  baked into any trace.  Env: PDTPU_FAULT_REPLICA_SLOW=
                  "ms[:every_n[:replica]]".
  net_delay       the fleet RPC's frame sender trickles every `every_n`-th
                  frame byte-chunk-by-byte with `ms` milliseconds between
                  chunks (default every frame) — the slowloris peer: a
                  frame that takes arbitrarily long to ASSEMBLE on the
                  receiving side while the socket stays healthy.  The
                  receiver's per-frame assembly deadline
                  (worker._FrameConn) must fence it with the typed
                  WireFormatError instead of holding the drive loop
                  hostage.  Consulted live per frame send, host-side
                  only.  Env: PDTPU_FAULT_NET_DELAY="ms[:every_n]".
  net_drop        the `n`-th RPC frame sent by this process (1-based,
                  counted across every connection) is cut MID-FRAME: half
                  its bytes go out, then the socket is hard-closed — a
                  connection reset in the middle of a length-prefixed
                  frame.  Fires once.  The receiver must fail typed
                  (WorkerDiedError on the closed peer / WireFormatError
                  on the torn frame), never decode garbage.  Env:
                  PDTPU_FAULT_NET_DROP="n".
  net_partition   a hard network partition against the replica with index
                  `replica`, lasting `secs` seconds from the first
                  consult after arming: every frame SENT to/from that
                  replica is silently blackholed and every receive sees
                  nothing, in BOTH directions, while both processes and
                  their sockets stay alive — the split-brain drill.  The
                  manager must fence on beat age and resubmit elsewhere;
                  the isolated worker must self-abort its residents after
                  the manager-silence timeout; a healed worker presenting
                  the stale epoch must be told to abort, never resume.
                  Arm it on BOTH sides (faults.enable locally + the
                  worker's `fault` RPC verb).  Env:
                  PDTPU_FAULT_NET_PARTITION="replica:secs".
  replica_wedge   the subprocess fleet worker with index `replica` blocks
                  INDEFINITELY inside its `tick`-th step (0-based) — a
                  hang, not a crash: the worker process stays alive, its
                  RPC socket stays connected, no exception ever raises.
                  The one failure mode PDTPU_FAULT_REPLICA_CRASH cannot
                  model, and exactly what the out-of-band heartbeat
                  exists to catch: the worker's heartbeat file goes
                  stale, the ReplicaManager fences the replica on
                  heartbeat AGE (no in-band call ever returns), SIGKILLs
                  the wedged process after the grace period, and the
                  supervisor restarts it under the backoff budget.
                  Consulted by the worker drive loop (serving/worker.py)
                  — in-process replicas share one driving thread, so
                  wedging one would wedge the fleet (the limitation that
                  motivates subprocess isolation).  Env:
                  PDTPU_FAULT_REPLICA_WEDGE="replica:tick".
  publish_corrupt the n-th weight artifact PUBLISHED by this process
                  (1-based, counted per process) is corrupted in place
                  POST-rename — truncated and bit-flipped AFTER the
                  atomic publish already made it visible, so the watch
                  signal fires on garbage bytes while the manifest
                  (written pre-rename) still names the good sha256.
                  The continuous-refresh pipeline must catch it at one
                  of its verify gates — the refresher's whole-file sha
                  check, the artifact channel's chunk verify, or the
                  post-flip canary — and keep serving the OLD weights;
                  corrupt weights must never reach a stream.  Consulted
                  by serving/refresh.py's WeightPublisher.  Env:
                  PDTPU_FAULT_PUBLISH_CORRUPT="n".
  adapter_corrupt the n-th LoRA adapter artifact READ by this process
                  (1-based, counted per process) is poisoned in memory
                  — a byte in the raw npz bytes is flipped AFTER the
                  file read but BEFORE any verification, so the loader
                  sees exactly what a torn ship / bad disk would hand
                  it.  The read path (lora.read_adapter) must reject
                  with a typed AdapterIntegrityError — never deliver
                  garbage factors to a slot — and the supervised caller
                  (worker load_adapter RPC, fleet.load_adapter)
                  re-ships/re-reads: the counter has advanced, so the
                  retry sees clean bytes.  Env:
                  PDTPU_FAULT_ADAPTER_CORRUPT="n".
  canary_diverge  while armed, the FleetRefresher's post-flip canary
                  gate reports a stream mismatch regardless of the real
                  comparison — the model-regressed-but-mechanically-
                  valid publish (bad training step, wrong checkpoint):
                  every byte verifies, yet the outputs changed.  The
                  refresher must roll the canary replica back to the
                  previous weights_sha, quarantine the publish, and
                  leave the whole fleet converged on the old weights.
                  Env: PDTPU_FAULT_CANARY_DIVERGE="1".

Deliberately import-light (no jax at module scope): DataLoader worker
processes and the bench orchestrator consult it before any backend exists.
"""
from __future__ import annotations

import os
import signal
import threading
from typing import Optional, Tuple

__all__ = ["enable", "disable", "reset", "get", "nan_grads_window",
           "poison_grads", "worker_crash_config", "maybe_crash_worker",
           "maybe_kill_mid_save", "backend_down", "nan_logits_request",
           "poison_logits", "slow_decode_config", "maybe_slow_decode",
           "draft_diverge_every", "poison_draft_logits", "kv_exhaust_cap",
           "prefix_evict_cap",
           "prefetch_stall_config", "maybe_stall_prefetch",
           "row_corrupt_fetch", "replica_crash_config",
           "replica_slow_config", "maybe_slow_replica",
           "replica_wedge_config", "maybe_wedge_replica",
           "net_delay_config", "net_drop_frame", "maybe_net_drop",
           "net_partition_config", "net_partition_active",
           "publish_corrupt_n", "maybe_corrupt_publish",
           "adapter_corrupt_n", "maybe_corrupt_adapter_read",
           "canary_diverge"]

_ENV = {
    "nan_grads": "PDTPU_FAULT_NAN_GRADS",
    "worker_crash": "PDTPU_FAULT_WORKER_CRASH",
    "kill_mid_save": "PDTPU_FAULT_KILL_MID_SAVE",
    "backend_down": "PDTPU_FAULT_BACKEND_DOWN",
    "nan_logits": "PDTPU_FAULT_NAN_LOGITS",
    "slow_decode": "PDTPU_FAULT_SLOW_DECODE",
    "draft_diverge": "PDTPU_FAULT_DRAFT_DIVERGE",
    "kv_exhaust": "PDTPU_FAULT_KV_EXHAUST",
    "prefix_evict": "PDTPU_FAULT_PREFIX_EVICT",
    "prefetch_stall": "PDTPU_FAULT_PREFETCH_STALL",
    "row_corrupt": "PDTPU_FAULT_ROW_CORRUPT",
    "replica_crash": "PDTPU_FAULT_REPLICA_CRASH",
    "replica_slow": "PDTPU_FAULT_REPLICA_SLOW",
    "replica_wedge": "PDTPU_FAULT_REPLICA_WEDGE",
    "net_delay": "PDTPU_FAULT_NET_DELAY",
    "net_drop": "PDTPU_FAULT_NET_DROP",
    "net_partition": "PDTPU_FAULT_NET_PARTITION",
    "publish_corrupt": "PDTPU_FAULT_PUBLISH_CORRUPT",
    "adapter_corrupt": "PDTPU_FAULT_ADAPTER_CORRUPT",
    "canary_diverge": "PDTPU_FAULT_CANARY_DIVERGE",
}

_lock = threading.Lock()
_registry = {}          # point -> raw config string (authoritative mirror)
_save_counter = {"n": 0}  # kill_mid_save is counted per process
_publish_counter = {"n": 0}  # publish_corrupt is counted per process
_adapter_counter = {"n": 0}  # adapter_corrupt is counted per process
_net_state = {"frames": 0, "drop_fired": False, "partitions": {}}


def enable(point: str, value="1"):
    """Arm a fault.  Mirrors into os.environ so worker subprocesses (fork /
    forkserver started after this call) and checkpoint subprocesses inherit
    it.  `value` is the point's config string (see module docstring)."""
    if point not in _ENV:
        raise ValueError(f"unknown fault point {point!r}; "
                         f"known: {sorted(_ENV)}")
    with _lock:
        _registry[point] = str(value)
        os.environ[_ENV[point]] = str(value)


def disable(point: str):
    with _lock:
        _registry.pop(point, None)
        os.environ.pop(_ENV[point], None)


def reset():
    """Disarm every fault (test teardown)."""
    for point in _ENV:
        disable(point)
    with _lock:
        _save_counter["n"] = 0
        _publish_counter["n"] = 0
        _adapter_counter["n"] = 0
        _net_state["frames"] = 0
        _net_state["drop_fired"] = False
        _net_state["partitions"] = {}


def get(point: str) -> Optional[str]:
    """Live config string for a point, or None.  Reads the registry first,
    then the env — so faults armed via the environment (subprocess tests)
    are seen without any enable() call in this process."""
    with _lock:
        v = _registry.get(point)
    if v is not None:
        return v
    return os.environ.get(_ENV[point])


# -- nan_grads ---------------------------------------------------------------

def nan_grads_window() -> Optional[Tuple[int, int]]:
    """[a, b) step window to poison, or None when disarmed.  Consulted at
    TRACE time by the compiled train steps: the window bounds are baked as
    constants, the comparison against step_no stays dynamic."""
    raw = get("nan_grads")
    if not raw:
        return None
    if ":" in raw:
        a, b = raw.split(":", 1)
        return int(a), int(b)
    k = int(raw)
    return k, k + 1


def poison_grads(grads, step_no):
    """Multiply every gradient leaf by NaN inside the poison window (traced;
    identity outside it).  RowSparseGrad leaves are poisoned through their
    .values so the sparse path is exercised too."""
    import jax.numpy as jnp
    from ..core.selected_rows import RowSparseGrad
    window = nan_grads_window()
    if window is None:
        return grads
    a, b = window
    bad = (step_no >= a) & (step_no < b)

    def leaf(g):
        if isinstance(g, RowSparseGrad):
            return RowSparseGrad(g.rows, leaf(g.values), g.dense_shape)
        factor = jnp.where(bad, jnp.asarray(float("nan"), g.dtype),
                           jnp.asarray(1.0, g.dtype))
        return g * factor
    return {k: leaf(g) for k, g in grads.items()}


# -- worker_crash ------------------------------------------------------------

def worker_crash_config() -> Optional[Tuple[str, int, Optional[str]]]:
    """(mode, seq, once_path) or None.  mode: "kill" | "exc"."""
    raw = get("worker_crash")
    if not raw:
        return None
    parts = raw.split(":", 2)
    if len(parts) == 1:  # bare seq -> kill
        return "kill", int(parts[0]), None
    mode = parts[0] if parts[0] in ("kill", "exc") else "kill"
    seq = int(parts[1] if parts[0] in ("kill", "exc") else parts[0])
    once = parts[2] if len(parts) == 3 else None
    return mode, seq, once


def maybe_crash_worker(seq: int):
    """Called by the DataLoader worker loop per task.  Fires at most once
    when a `once` sentinel path is configured (the sentinel is created
    BEFORE dying so the respawned worker survives the retried batch)."""
    cfg = worker_crash_config()
    if cfg is None:
        return
    mode, target, once = cfg
    if seq != target:
        return
    if once is not None:
        if os.path.exists(once):
            return
        open(once, "w").close()
    if mode == "exc":
        raise RuntimeError(f"injected worker exception at seq {seq}")
    os._exit(17)  # hard crash: no result, no cleanup — the real thing


# -- kill_mid_save -----------------------------------------------------------

def maybe_kill_mid_save():
    """Called by the checkpoint writer after the shard/manifest files are on
    disk but BEFORE the atomic rename publishes them.  SIGKILL — not
    sys.exit — so no finally/atexit softens the crash."""
    raw = get("kill_mid_save")
    if not raw:
        return
    with _lock:
        _save_counter["n"] += 1
        n = _save_counter["n"]
    if n >= int(raw):
        os.kill(os.getpid(), signal.SIGKILL)


# -- publish_corrupt ---------------------------------------------------------

def publish_corrupt_n() -> Optional[int]:
    """Which publish (1-based, per process) to corrupt, or None."""
    raw = get("publish_corrupt")
    if not raw:
        return None
    return int(raw)


def maybe_corrupt_publish(path: str) -> bool:
    """Called by the WeightPublisher AFTER the atomic rename made the
    weight artifact at `path` visible.  Counts publishes per process; on
    the n-th, the artifact is truncated and bit-flipped IN PLACE — the
    manifest written pre-rename still names the good sha256, so the
    corruption is exactly what a torn write / bad disk after the rename
    looks like to a watcher.  Returns True when it fired.  One of the
    refresh pipeline's verify gates (whole-file sha check, chunked ship
    verify, canary) must catch it; corrupt weights must never serve."""
    n = publish_corrupt_n()
    if n is None:
        return False
    with _lock:
        _publish_counter["n"] += 1
        cnt = _publish_counter["n"]
    if cnt != n:
        return False
    try:
        size = os.path.getsize(path)
        keep = max(1, int(size * 0.7))
        with open(path, "r+b") as f:
            f.truncate(keep)
            pos = max(0, keep // 2)
            f.seek(pos)
            b = f.read(1)
            f.seek(pos)
            f.write(bytes([b[0] ^ 0xFF]) if b else b"\xff")
    except OSError:
        pass  # a vanished file corrupts even harder
    return True


# -- adapter_corrupt ---------------------------------------------------------

def adapter_corrupt_n() -> Optional[int]:
    """Which adapter artifact read (1-based, per process) to poison, or
    None."""
    raw = get("adapter_corrupt")
    if not raw:
        return None
    return int(raw)


def maybe_corrupt_adapter_read(raw: bytes, path: str = "") -> bytes:
    """Called by `lora.read_adapter` on the raw artifact bytes BEFORE
    verification.  Counts reads per process; on the n-th, flips one byte
    in the middle of the buffer — the loader's integrity checks must
    turn this into a typed AdapterIntegrityError (garbage factors must
    never reach a device slot), and the supervised caller re-ships.  The
    file on disk is untouched, so the retry succeeds."""
    n = adapter_corrupt_n()
    if n is None:
        return raw
    with _lock:
        _adapter_counter["n"] += 1
        cnt = _adapter_counter["n"]
    if cnt != n:
        return raw
    if not raw:
        return b"\xff"
    buf = bytearray(raw)
    pos = len(buf) // 2
    buf[pos] ^= 0xFF
    return bytes(buf)


# -- canary_diverge ----------------------------------------------------------

def canary_diverge() -> bool:
    """True while armed: the post-flip canary gate must report a stream
    mismatch regardless of the real comparison, exercising auto-rollback
    end to end (serving/refresh.py)."""
    return bool(get("canary_diverge"))


# -- nan_logits --------------------------------------------------------------

def nan_logits_request() -> Optional[int]:
    """Submission sequence number (0-based) of the serving request whose
    decode logits get poisoned, or None when disarmed.  Consulted at decode
    TRACE time for presence (so the clean decode program has zero fault
    branches); the engine maps the sequence number to a per-slot poison
    mask passed as a dynamic input."""
    raw = get("nan_logits")
    if not raw:
        return None
    return int(raw)


def poison_logits(logits, poison_mask):
    """Multiply each poisoned row of (S, V) logits by NaN (traced; identity
    rows elsewhere).  Only ever traced into the decode program when
    nan_logits is armed at engine-construction time."""
    import jax.numpy as jnp
    factor = jnp.where(poison_mask, jnp.float32(float("nan")),
                       jnp.float32(1.0))
    return logits * factor[:, None]


# -- draft_diverge -----------------------------------------------------------

def draft_diverge_every() -> Optional[int]:
    """Tick stride N (poison the draft every N-th speculative tick,
    0-based: ticks 0, N, 2N, ...), or None when disarmed.  Consulted at
    verify TRACE time for presence (the clean verify program carries zero
    fault branches); which tick diverges is a dynamic input the engine
    computes host-side per call."""
    raw = get("draft_diverge")
    if not raw:
        return None
    return max(1, int(raw))


def poison_draft_logits(logits, diverge):
    """Negate the draft logits when `diverge` (traced bool) is set: the
    draft proposes its LEAST-likely token, which the target all but
    certainly rejects — a finite corruption (never NaN) so the engine's
    non-finite guard stays out of the picture and the degradation under
    test is purely accept-rate -> throughput.  Only ever traced into the
    verify program when draft_diverge is armed at engine construction."""
    import jax.numpy as jnp
    return jnp.where(diverge, -logits, logits)


# -- slow_decode -------------------------------------------------------------

def slow_decode_config() -> Optional[Tuple[float, int]]:
    """(sleep_ms, every_n) or None when disarmed.  Consulted live per
    decode call (host-side only — nothing is baked into any trace), so a
    running engine reacts to arm/disarm immediately."""
    raw = get("slow_decode")
    if not raw:
        return None
    parts = raw.split(":", 1)
    ms = float(parts[0])
    every = int(parts[1]) if len(parts) == 2 else 1
    return ms, max(1, every)


def maybe_slow_decode(call_no: int) -> float:
    """Host-side sleep before decode call number `call_no` (0-based) when
    slow_decode is armed and call_no hits the every_n stride.  Returns the
    seconds slept (0.0 when disarmed / off-stride)."""
    cfg = slow_decode_config()
    if cfg is None:
        return 0.0
    ms, every = cfg
    if call_no % every:
        return 0.0
    import time
    secs = ms / 1000.0
    time.sleep(secs)
    return secs


# -- kv_exhaust --------------------------------------------------------------

def kv_exhaust_cap() -> Optional[int]:
    """Forced block-pool capacity (the allocator pretends only N blocks
    exist), or None when disarmed.  Consulted LIVE on every allocator
    call — pure host bookkeeping, no trace ever sees it — so a running
    engine reacts to arm/disarm immediately."""
    raw = get("kv_exhaust")
    if not raw:
        return None
    return max(0, int(raw))


# -- prefix_evict ------------------------------------------------------------

def prefix_evict_cap() -> Optional[int]:
    """Forced cap on RESIDENT refcount-0 prefix-cache blocks, or None
    when disarmed.  Consulted LIVE on every cache release/insert — pure
    host bookkeeping, no trace ever sees it — so a running engine reacts
    to arm/disarm immediately.  N=0 disables retention entirely."""
    raw = get("prefix_evict")
    if not raw:
        return None
    return max(0, int(raw))


# -- prefetch_stall ----------------------------------------------------------

def prefetch_stall_config() -> Optional[Tuple[float, int]]:
    """(sleep_ms, every_n) or None when disarmed.  Consulted live per host
    row fetch (host-side only; nothing baked into any trace), so a running
    prefetch pipeline reacts to arm/disarm immediately."""
    raw = get("prefetch_stall")
    if not raw:
        return None
    parts = raw.split(":", 1)
    ms = float(parts[0])
    every = int(parts[1]) if len(parts) == 2 else 1
    return ms, max(1, every)


def maybe_stall_prefetch(fetch_no: int) -> float:
    """Host-side sleep before fetch number `fetch_no` (0-based) when
    prefetch_stall is armed and the stride hits.  Returns seconds slept."""
    cfg = prefetch_stall_config()
    if cfg is None:
        return 0.0
    ms, every = cfg
    if fetch_no % every:
        return 0.0
    import time
    secs = ms / 1000.0
    time.sleep(secs)
    return secs


# -- row_corrupt -------------------------------------------------------------

def row_corrupt_fetch() -> Optional[int]:
    """1-based fetch number whose prefetched row slab gets one row
    poisoned with NaN (the fetched COPY, never the host table), or None
    when disarmed.  The pipeline's consume-side verify must detect the
    poison and refetch."""
    raw = get("row_corrupt")
    if not raw:
        return None
    return int(raw)


# -- replica_crash / replica_slow --------------------------------------------

def replica_crash_config() -> Optional[Tuple[int, int]]:
    """(replica_index, tick) at which the targeted fleet replica dies
    abruptly, or None when disarmed.  Consulted live per replica step by
    the ReplicaManager (host-side only), so it can be armed on a running
    fleet."""
    raw = get("replica_crash")
    if not raw:
        return None
    replica, tick = raw.split(":", 1)
    return int(replica), int(tick)


def replica_slow_config() -> Optional[Tuple[float, int, Optional[int]]]:
    """(sleep_ms, every_n, replica_or_None) — the brownout knob, or None
    when disarmed.  A None replica field slows EVERY replica; an index
    slows only that one (the probe's targeted brownout).  Consulted live
    per replica step, nothing baked into any trace."""
    raw = get("replica_slow")
    if not raw:
        return None
    parts = raw.split(":", 2)
    ms = float(parts[0])
    every = int(parts[1]) if len(parts) >= 2 else 1
    replica = int(parts[2]) if len(parts) == 3 else None
    return ms, max(1, every), replica


def maybe_slow_replica(replica_idx: int, step_no: int) -> float:
    """Host-side sleep before step `step_no` (0-based) of replica
    `replica_idx` when replica_slow is armed, the stride hits, and the
    replica matches (or no replica is targeted).  Returns seconds
    slept."""
    cfg = replica_slow_config()
    if cfg is None:
        return 0.0
    ms, every, target = cfg
    if target is not None and target != replica_idx:
        return 0.0
    if step_no % every:
        return 0.0
    import time
    secs = ms / 1000.0
    time.sleep(secs)
    return secs


def replica_wedge_config() -> Optional[Tuple[int, int]]:
    """(replica_index, tick) at which the targeted subprocess worker's
    step BLOCKS forever (hang, not crash), or None when disarmed.
    Consulted live per worker step by the worker drive loop — the
    injection that proves OUT-OF-BAND heartbeat detection: the process
    stays alive and connected, so only heartbeat age can see it."""
    raw = get("replica_wedge")
    if not raw:
        return None
    replica, tick = raw.split(":", 1)
    return int(replica), int(tick)


def maybe_wedge_replica(replica_idx: int, step_no: int):
    """Block FOREVER when replica_wedge is armed for (replica_idx,
    step_no) — the wedged-worker hang.  Never returns once it fires;
    the manager's SIGKILL is the only way out (which is the point)."""
    cfg = replica_wedge_config()
    if cfg is None or cfg[0] != replica_idx or step_no < cfg[1]:
        # >= not ==: the knob is usually armed over RPC against a live,
        # fast-stepping worker — an exact-tick match could slip past
        # between the arm and the next step, and a wedge that never
        # fires is a vacuous chaos test
        return
    import time
    while True:  # pragma: no cover — exits only via SIGKILL
        time.sleep(3600)


# -- net_delay / net_drop / net_partition ------------------------------------

def net_delay_config() -> Optional[Tuple[float, int]]:
    """(chunk_sleep_ms, every_n) or None when disarmed — the slowloris
    knob.  Consulted live per frame SEND by the fleet RPC
    (worker._FrameConn): a matched frame is dribbled out in small byte
    chunks with `ms` sleeps between them, so its assembly on the peer
    takes arbitrarily long while the socket stays healthy."""
    raw = get("net_delay")
    if not raw:
        return None
    parts = raw.split(":", 1)
    ms = float(parts[0])
    every = int(parts[1]) if len(parts) == 2 else 1
    return ms, max(1, every)


def net_drop_frame() -> Optional[int]:
    """1-based frame number (counted across every connection in this
    process) to cut mid-frame, or None when disarmed."""
    raw = get("net_drop")
    if not raw:
        return None
    return int(raw)


def maybe_net_drop() -> bool:
    """Count one frame send; True exactly once, on the armed frame
    number — the caller sends HALF the frame and hard-closes the socket
    (a mid-frame connection cut).  Single-shot per process until
    reset()."""
    target = net_drop_frame()
    if target is None:
        return False
    with _lock:
        _net_state["frames"] += 1
        if _net_state["drop_fired"] or _net_state["frames"] != target:
            return False
        _net_state["drop_fired"] = True
    return True


def net_partition_config() -> Optional[Tuple[int, float]]:
    """(replica_index, seconds) or None when disarmed."""
    raw = get("net_partition")
    if not raw:
        return None
    replica, secs = raw.split(":", 1)
    return int(replica), float(secs)


def net_partition_active(replica_idx: Optional[int]) -> bool:
    """True while the partition window against `replica_idx` is open.
    The window starts at the FIRST consult after arming (each process
    starts its own clock — arm both sides near-simultaneously: the
    manager via enable(), the worker via its `fault` RPC verb) and
    closes `secs` later: the partition HEALS, with both processes still
    alive — the split-brain reconciliation this knob exists to force."""
    cfg = net_partition_config()
    if cfg is None or replica_idx is None or cfg[0] != int(replica_idx):
        return False
    raw = get("net_partition")
    import time
    now = time.monotonic()
    with _lock:
        start = _net_state["partitions"].get(raw)
        if start is None:
            start = now
            _net_state["partitions"][raw] = start
    return (now - start) < cfg[1]


# -- backend_down ------------------------------------------------------------

def backend_down() -> bool:
    return bool(get("backend_down"))
