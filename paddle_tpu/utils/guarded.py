"""GuardedTrainStep — the host-side half of step guarding.

The device half (jit.guard_select, compiled into TrainStep /
ShardedTrainStep via guard=True) computes loss/global-grad-norm finiteness
inside the compiled step and skips bad updates on-device with no extra
host sync.  This wrapper adds the policy around it:

- reads (loss, grad_norm, ok) together — ONE host sync per step, the same
  one the caller's float(loss) already paid;
- skip-and-decay: a nonfinite step feeds an attached GradScaler's dynamic
  loss-scale state machine (record_skip) even when AMP is off;
- loss-spike detection against a rolling window of recent finite losses
  (a spiking-but-finite step can't be skipped retroactively — it counts
  toward the bad streak and the rollback handles sustained divergence);
- after `max_bad_steps` CONSECUTIVE bad steps it rolls back to the last
  checkpoint and writes a structured quarantine record
  (<ckpt_dir>/quarantine.jsonl) naming the step span, reason, loss and
  grad norm — the post-mortem artifact the reference's silent NaN crashes
  never left behind.

Usage:
    step = jit.TrainStep(model, loss_fn, opt, guard=True)
    gstep = GuardedTrainStep(step, checkpoint_dir=ckpt, max_bad_steps=3)
    for batch in loader:
        loss = gstep(*batch)
        if gstep.last_skipped:
            continue  # optionally retry the batch
"""
from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Optional

__all__ = ["GuardedTrainStep"]


class GuardedTrainStep:
    """Policy wrapper over a guard-enabled TrainStep/ShardedTrainStep."""

    def __init__(self, step, checkpoint_dir: Optional[str] = None,
                 scaler=None, spike_window: int = 32,
                 spike_factor: float = 8.0, min_window: int = 8,
                 max_bad_steps: int = 3):
        if getattr(step, "_compiled", None) is not None and not step._guard:
            raise ValueError(
                "GuardedTrainStep needs the inner step built with "
                "guard=True (it already compiled without the guard)")
        step._guard = True
        self.step = step
        self.checkpoint_dir = checkpoint_dir
        self.scaler = scaler
        self.spike_factor = float(spike_factor)
        self.min_window = int(min_window)
        self.max_bad_steps = int(max_bad_steps)
        self._window: deque = deque(maxlen=int(spike_window))
        self.bad_streak = 0
        self.quarantine: list = []
        self.last_skipped = False
        self.last_reason: Optional[str] = None

    # passthroughs ----------------------------------------------------------
    @property
    def model(self):
        return self.step.model

    @property
    def optimizer(self):
        return self.step.optimizer

    def save_checkpoint(self, directory=None, step=None, extra_meta=None,
                        data_cursor=None):
        return self.step.save_checkpoint(
            directory or self.checkpoint_dir, step=step,
            extra_meta=extra_meta, scaler=self.scaler,
            data_cursor=data_cursor)

    def restore_checkpoint(self, directory=None):
        return self.step.restore_checkpoint(directory or self.checkpoint_dir,
                                            scaler=self.scaler)

    # the guarded call ------------------------------------------------------
    def __call__(self, *batch):
        import numpy as np
        loss_t = self.step(*batch)
        gnorm_d, ok_d = self.step.last_guard
        # one fused host read for loss/gnorm/ok (the loss read was already
        # the step's host sync point)
        loss, gnorm, ok = (float(np.asarray(loss_t._data)),
                           float(np.asarray(gnorm_d)),
                           bool(np.asarray(ok_d)))
        reason = None
        if not ok:
            reason = "nonfinite"
        elif self._is_spike(loss):
            reason = "loss_spike"
        if reason is None:
            self._window.append(loss)
            self.bad_streak = 0
            self.last_skipped = False
            self.last_reason = None
        else:
            self._on_bad_step(reason, loss, gnorm)
        return loss_t

    def _is_spike(self, loss: float) -> bool:
        if len(self._window) < self.min_window:
            return False
        med = sorted(self._window)[len(self._window) // 2]
        return abs(loss) > self.spike_factor * max(abs(med), 1e-12)

    def _on_bad_step(self, reason: str, loss: float, gnorm: float):
        from .monitor import stat_add
        stat_add("STAT_guarded_bad_steps")
        self.bad_streak += 1
        self.last_skipped = reason == "nonfinite"  # spikes were applied
        self.last_reason = reason
        rec = {"step": int(self.step.optimizer._step_count),
               "reason": reason, "loss": loss, "grad_norm": gnorm,
               "bad_streak": self.bad_streak, "time": time.time(),
               "skipped_on_device": self.last_skipped}
        if reason == "nonfinite" and self.scaler is not None:
            # skip-and-decay: drive the dynamic loss-scale state machine
            # from the on-device verdict (no per-grad host isfinite pass)
            self.scaler.record_skip()
            rec["loss_scale"] = self.scaler.get_init_loss_scaling()
        if (self.bad_streak >= self.max_bad_steps
                and self.checkpoint_dir is not None):
            from ..observability import span as _span
            with _span("guarded_rollback", args={"reason": reason}):
                meta = self.restore_checkpoint()
            if meta is not None:
                rec["rolled_back_to"] = meta["step"]
                stat_add("STAT_guarded_rollbacks")
                self.bad_streak = 0
                self._window.clear()
            else:
                # no checkpoint to roll back to: nothing was restored, so
                # the streak and spike window must survive (resetting them
                # would rebaseline spike detection on the diverged losses)
                rec["rolled_back_to"] = None
                rec["rollback_failed"] = "no restorable checkpoint"
                stat_add("STAT_guarded_rollback_failures")
        self.quarantine.append(rec)
        self._append_quarantine(rec)

    def _append_quarantine(self, rec: dict):
        if self.checkpoint_dir is None:
            return
        try:
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            with open(os.path.join(self.checkpoint_dir,
                                   "quarantine.jsonl"), "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError:
            pass  # quarantine bookkeeping must never kill the run
