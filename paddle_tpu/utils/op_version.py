"""Op-version registry — saved-artifact compatibility tracking.

Reference: paddle/fluid/framework/op_version_registry.h:1 — every op whose
serialized semantics change bumps a registered version; saved programs
embed the version map and loaders check compatibility.

TPU-native: the registry versions the SEMANTIC surfaces that affect a
serialized artifact (exported StableHLO + weights): op families whose
numerics/layout changed across framework revisions.  `jit.save` embeds
`snapshot()` in the artifact metadata; `jit.load` calls `check_compat` —
an artifact carrying a NEWER version than this runtime errors (it may
rely on semantics this build doesn't have); an older one loads (StableHLO
is the stable interchange layer, reference Proto IR role).
"""
from __future__ import annotations

import warnings
from typing import Dict, Optional

__all__ = ["register_op_version", "get_op_version", "snapshot",
           "check_compat", "OpVersionError"]


class OpVersionError(RuntimeError):
    pass


_REGISTRY: Dict[str, int] = {}
_NOTES: Dict[str, list] = {}


def register_op_version(op: str, version: int, note: str = ""):
    """Declare `op`'s current serialized-semantics version (monotone)."""
    cur = _REGISTRY.get(op, 0)
    if version < cur:
        raise ValueError(f"{op}: version {version} < registered {cur}")
    _REGISTRY[op] = version
    if note:
        _NOTES.setdefault(op, []).append((version, note))


def get_op_version(op: str) -> Optional[int]:
    return _REGISTRY.get(op)


def snapshot() -> Dict[str, int]:
    return dict(_REGISTRY)


def check_compat(saved: Dict[str, int], strict: bool = False):
    """Validate an artifact's embedded version map against this runtime.

    - saved newer than runtime -> OpVersionError (can't honor semantics)
    - saved older -> ok (forward-compatible interchange format)
    - op unknown to this runtime -> warning (strict=True -> error)
    """
    for op, ver in (saved or {}).items():
        cur = _REGISTRY.get(op)
        if cur is None:
            msg = (f"artifact references op {op!r} (v{ver}) unknown to "
                   "this runtime")
            if strict:
                raise OpVersionError(msg)
            warnings.warn(msg)
        elif ver > cur:
            raise OpVersionError(
                f"artifact op {op!r} v{ver} is newer than this runtime's "
                f"v{cur}; upgrade paddle_tpu to load it")


# -- current semantic versions ----------------------------------------------
# r1 -> r2 changes that altered serialized numerics/layout:
register_op_version("flash_attention", 2,
                    "natural-layout head-folded kernels; in-kernel "
                    "dropout/mask (r1 was transpose-layout, fwd-only)")
register_op_version("scaled_dot_product_attention", 2,
                    "routes masks/dropout through the flash kernel")
register_op_version("fake_quantize", 1, "QAT/PTQ fake-quant family")
register_op_version("sequence_ops", 1, "padded+lengths ragged toolkit")
register_op_version("detection_ops", 1, "vision.ops box/NMS/RoI family")
register_op_version("exported_program", 1,
                    "StableHLO via jax.export + npz weights")
# ISSUE-10: the fused conv-net epilogue family grew pooled (bn+act+pool)
# and dual-BN (downsample-add) variants, and the fallback paths switched
# to recompute backwards — bumping here rolls the persistent program
# store's content-addressed namespace (programs/store.py folds the full
# snapshot into the cache dir name) so no stale pre-epilogue artifact can
# be reused silently.
register_op_version("fused_bn_act", 2,
                    "pooled + dual-BN epilogues; recompute-backward "
                    "fallbacks (v1: PR-1 bn/act/residual only)")
register_op_version("fused_ce", 2,
                    "fused_pool_linear_cross_entropy classifier tail "
                    "(v1: token-chunked tied-head CE only)")
