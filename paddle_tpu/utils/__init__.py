"""paddle_tpu.utils."""
from . import checkpoint, flags, profiler  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401

def try_import(name):
    import importlib
    return importlib.import_module(name)
from . import monitor  # noqa: F401,E402
