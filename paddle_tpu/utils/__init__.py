"""paddle_tpu.utils."""
from . import checkpoint, faults, flags, profiler, retry  # noqa: F401
from .flags import get_flags, set_flags  # noqa: F401
from .retry import RetryPolicy, retry_call  # noqa: F401


def __getattr__(name):
    # lazy: guarded pulls in jit (which pulls the op/layer stack) — keep
    # `import paddle_tpu.utils` light and cycle-free
    if name == "guarded":
        from . import guarded
        return guarded
    if name == "GuardedTrainStep":
        from .guarded import GuardedTrainStep
        return GuardedTrainStep
    raise AttributeError(name)


def dump_config(path=None):
    """paddle.utils.dump_config — the reference lists this in
    utils/__init__.py:28 __all__ without ever defining it (a phantom of
    the era).  Here it does what the name promises: dump the live FLAGS
    registry as JSON to `path`, or return the dict."""
    import json
    snapshot = dict(flags._FLAGS)
    if path is not None:
        with open(path, "w") as f:
            json.dump(snapshot, f, indent=2, default=str)
        return path
    return snapshot

def try_import(name):
    import importlib
    return importlib.import_module(name)
from . import monitor  # noqa: F401,E402


def deprecated(update_to="", since="", reason=""):
    """Decorator marking an API deprecated (reference: utils/deprecated.py)
    — warns once per call site."""
    import functools
    import warnings

    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return decorate


def run_check():
    """Sanity-check the installation (reference: utils/install_check.py
    run_check): one tiny forward+backward+optimizer step on the current
    backend, and a sharded step when multiple devices exist."""
    import numpy as np
    import jax
    from .. import nn, optimizer, to_tensor
    from ..nn import functional as F
    from .. import seed as _seed
    _seed(0)
    model = nn.Linear(4, 2)
    opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    x = to_tensor(np.ones((2, 4), "float32"))
    loss = F.mse_loss(model(x), to_tensor(np.zeros((2, 2), "float32")))
    loss.backward()
    opt.step()
    opt.clear_grad()
    n = len(jax.devices())
    if n > 1:  # exercise a cross-device reduction over a dp mesh
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel import create_mesh
        mesh = create_mesh({"dp": n})
        arr = jax.device_put(
            np.ones((n, 4), np.float32), NamedSharding(mesh, P("dp")))
        total = float(jax.jit(lambda a: (a * 2).sum())(arr))
        assert total == n * 8.0, "sharded reduction failed"
    print(f"paddle_tpu is installed successfully! "
          f"(backend={jax.default_backend()}, {n} device(s))")
    return True


class _UniqueNameGenerator:
    """reference: fluid/unique_name.py generate/guard/switch."""

    def __init__(self):
        self._counters = {}

    def generate(self, key):
        i = self._counters.get(key, 0)
        self._counters[key] = i + 1
        return f"{key}_{i}"

    def switch(self, new_counters=None):
        """Swap the counter table; returns the previous one."""
        old = self._counters
        self._counters = new_counters if new_counters is not None else {}
        return old

    def guard(self, new_generator=None):
        """Context manager giving a fresh (or provided) name space."""
        import contextlib

        @contextlib.contextmanager
        def _guard():
            old = self.switch({} if new_generator is None
                              else dict(new_generator))
            try:
                yield self
            finally:
                self.switch(old)
        return _guard()


unique_name = _UniqueNameGenerator()


def download(url, path=None, md5sum=None):
    raise RuntimeError(
        "downloads are unavailable in this zero-egress environment; place "
        "files locally and point the dataset/model APIs at them")
