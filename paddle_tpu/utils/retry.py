"""Shared retry policy: exponential backoff + jitter, deadline, filters.

Reference: the ad-hoc retry loops scattered through the reference's host
services (fs.py HDFS shell retries, PS client reconnect loops, reader.py
worker restarts).  TPU-native stance: one policy object owns the backoff
schedule so every host-side service that talks to something flaky — the
DataLoader worker pool, checkpoint filesystems, the bench backend probe —
degrades the same way and is testable the same way.

Deliberately dependency-free (no jax import): worker processes and the
bench orchestrator both use it before any backend exists.
"""
from __future__ import annotations

import random
import time
from typing import Callable, Iterable, Optional, Tuple, Type

__all__ = ["RetryPolicy", "retry_call", "RetriesExhausted", "Deadline"]


class Deadline:
    """A wall-clock budget anchored at creation time.

    The deadline RetryPolicy.call enforces across attempts, factored out
    so other host services (the serving RequestScheduler's per-request
    deadlines, cancellation sweeps) count down against the same clock
    object instead of re-deriving `start + budget` arithmetic per call
    site.  `seconds=None` means unbounded (never expires).
    """

    __slots__ = ("seconds", "_t0", "_clock")

    def __init__(self, seconds: Optional[float] = None,
                 _clock: Callable[[], float] = time.monotonic):
        self.seconds = None if seconds is None else float(seconds)
        self._clock = _clock
        self._t0 = _clock()

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> Optional[float]:
        """Seconds left, or None when unbounded.  Never negative."""
        if self.seconds is None:
            return None
        return max(0.0, self.seconds - self.elapsed())

    def expired(self) -> bool:
        return self.seconds is not None and self.elapsed() >= self.seconds

    def __repr__(self):
        if self.seconds is None:
            return "Deadline(unbounded)"
        return f"Deadline({self.seconds}s, remaining={self.remaining():.3f}s)"


class RetriesExhausted(Exception):
    """All attempts failed.  `.last` carries the final attempt's exception
    (also chained as __cause__); `.attempts` the number made."""

    def __init__(self, msg, last: BaseException, attempts: int):
        super().__init__(msg)
        self.last = last
        self.attempts = attempts


class RetryPolicy:
    """Exponential backoff with full jitter and an optional wall deadline.

    retries:     additional attempts after the first (retries=3 -> up to 4
                 calls)
    base_delay:  sleep before the first retry; doubles each retry
    max_delay:   cap on a single sleep
    jitter:      fraction of the delay drawn uniformly at random and added
                 (0.5 -> sleep in [d, 1.5d]); decorrelates thundering herds
    deadline:    total wall-clock budget in seconds across all attempts;
                 exceeded -> RetriesExhausted even with retries left
    retry_on:    exception classes that trigger a retry
    giveup_on:   exception classes re-raised immediately even if they match
                 retry_on (checked first)
    """

    def __init__(self, retries: int = 3, base_delay: float = 0.1,
                 max_delay: float = 5.0, jitter: float = 0.5,
                 deadline: Optional[float] = None,
                 retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                 giveup_on: Tuple[Type[BaseException], ...] = (),
                 on_retry: Optional[Callable] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self.retries = max(0, int(retries))
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retry_on = tuple(retry_on)
        self.giveup_on = tuple(giveup_on)
        self.on_retry = on_retry  # on_retry(attempt_no, exc, next_delay)
        self._sleep = sleep

    def delays(self) -> Iterable[float]:
        """The backoff schedule (pre-jitter), one entry per retry."""
        d = self.base_delay
        for _ in range(self.retries):
            yield min(d, self.max_delay)
            d *= 2.0

    def call(self, fn: Callable, *args, **kwargs):
        """Run fn until it succeeds, a non-retryable error escapes, the
        attempt budget empties, or the deadline passes."""
        dl = Deadline(self.deadline)
        attempt = 0
        delays = iter(self.delays())
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.giveup_on:
                raise
            except self.retry_on as e:
                try:
                    delay = next(delays)
                except StopIteration:
                    raise RetriesExhausted(
                        f"{getattr(fn, '__name__', fn)!s} failed after "
                        f"{attempt} attempts: {type(e).__name__}: {e}",
                        e, attempt) from e
                if self.jitter:
                    delay += random.uniform(0.0, self.jitter * delay)
                remaining = dl.remaining()
                if remaining is not None and (dl.expired()
                                              or delay > remaining):
                    raise RetriesExhausted(
                        f"{getattr(fn, '__name__', fn)!s} exceeded the "
                        f"{self.deadline}s retry deadline after {attempt} "
                        f"attempts: {type(e).__name__}: {e}", e, attempt) from e
                if self.on_retry is not None:
                    self.on_retry(attempt, e, delay)
                self._sleep(delay)

    def wraps(self, fn: Callable) -> Callable:
        """Decorator form."""
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapper


def retry_call(fn: Callable, *args, retries: int = 3, base_delay: float = 0.1,
               max_delay: float = 5.0, jitter: float = 0.5,
               deadline: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (Exception,),
               giveup_on: Tuple[Type[BaseException], ...] = (),
               on_retry: Optional[Callable] = None, **kwargs):
    """One-shot convenience over RetryPolicy.call."""
    return RetryPolicy(retries=retries, base_delay=base_delay,
                       max_delay=max_delay, jitter=jitter, deadline=deadline,
                       retry_on=retry_on, giveup_on=giveup_on,
                       on_retry=on_retry).call(fn, *args, **kwargs)
