"""Distributed environment / bootstrap.

Reference: RoleMaker env parsing + gloo rendezvous
(python/paddle/distributed/fleet/base/role_maker.py), nccl-id TCP exchange
(c_gen_nccl_id_op.cc, imperative/nccl_context.cc), ParallelEnv
(python/paddle/fluid/dygraph/parallel.py).

TPU-native: `jax.distributed.initialize` replaces the entire bootstrap — the
coordinator address takes the role of both the gloo HTTP store and the
ncclUniqueId exchange; afterwards every process sees the global device set
and XLA handles cross-host collectives over ICI/DCN.
"""
from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


class ParallelEnv:
    """Per-process view of the distributed run (paddle.distributed.ParallelEnv)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        try:
            self._rank = jax.process_index()
            self._world_size = jax.process_count()
        except Exception:
            pass

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def local_rank(self):
        return self._rank

    @property
    def nranks(self):
        return self._world_size

    @property
    def dev_id(self):
        return 0

    @property
    def device_type(self):
        return jax.default_backend()

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


def init_parallel_env(strategy=None) -> ParallelEnv:
    """paddle.distributed.init_parallel_env — multi-process bootstrap.

    Single-process (the common TPU single-controller case): no-op.
    Multi-process (PADDLE_TRAINERS_NUM > 1): jax.distributed.initialize with
    the first endpoint as coordinator.
    """
    global _initialized
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if nranks > 1 and not _initialized:
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        coordinator = os.environ.get("PADDLE_COORDINATOR", eps[0] if eps[0]
                                     else None)
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=nranks, process_id=rank)
        _initialized = True
    return ParallelEnv()


def get_rank() -> int:
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size() -> int:
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def is_initialized() -> bool:
    return _initialized or get_world_size() == 1
