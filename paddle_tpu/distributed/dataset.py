"""Dataset engine: InMemoryDataset / QueueDataset.

Reference: paddle/fluid/framework/data_set.h:40-111 (DatasetImpl:
LoadIntoMemory over many files x many threads, LocalShuffle,
GlobalShuffle across trainers, ReleaseMemory, memory-size queries) and
python/paddle/distributed/fleet/dataset/dataset.py (the 2.0 facade:
init/set_filelist/load_into_memory/global_shuffle).

TPU-native redesign:
- LoadIntoMemory parses with the native C++ threaded datafeed
  (native/src/datafeed.cc) when it builds, falling back to a Python
  parser of the same `label<TAB>f1 f2 ...` text format.
- GlobalShuffle needs no parameter-server scatter: every rank derives the
  SAME seeded permutation of the global sample set and then iterates only
  its rank's strided shard — the outcome (each sample visited once
  per epoch by exactly one trainer, order globally random) matches the
  reference's PS-mediated shuffle without any cross-host traffic.
- QueueDataset streams batches straight off the native feed (single
  pass, nothing held in memory) — data_set.h's non-memory mode.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["InMemoryDataset", "QueueDataset"]


def _parse_text_py(path: str, dim: int):
    feats, labels = [], []
    with open(path) as f:
        for line in f:
            parts = line.strip().replace(",", " ").split()
            if len(parts) != dim + 1:
                continue
            try:
                labels.append(int(parts[0]))
                feats.append([float(v) for v in parts[1:]])
            except ValueError:
                continue
    return (np.asarray(feats, np.float32).reshape(-1, dim),
            np.asarray(labels, np.int64))


def _parse_binary_py(path: str, dim: int):
    """Fixed records of int64 label + dim float32 (the format
    native.write_binary_slot_file emits)."""
    rec = np.dtype([("label", "<i8"), ("feat", "<f4", (dim,))])
    data = np.fromfile(path, dtype=rec)
    return (np.ascontiguousarray(data["feat"], np.float32),
            np.ascontiguousarray(data["label"], np.int64))


def _parse_file_py(path: str, dim: int, binary: bool):
    return (_parse_binary_py if binary else _parse_text_py)(path, dim)


class _DatasetBase:
    def __init__(self):
        self._filelist: List[str] = []
        self._batch_size = 1
        self._thread_num = 1
        self._dim: Optional[int] = None
        self._binary = False
        self._drop_last = False

    # -- fleet-style configuration (reference: dataset.py init/set_*) --------
    def init(self, batch_size=1, thread_num=1, feature_dim=None,
             use_var=None, binary=False, drop_last=False, **kw):
        self._batch_size = int(batch_size)
        self._thread_num = int(thread_num)
        if feature_dim is not None:
            self._dim = int(feature_dim)
        self._binary = bool(binary)
        self._drop_last = bool(drop_last)

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def get_filelist(self):
        return list(self._filelist)

    def set_batch_size(self, batch_size: int):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num: int):
        self._thread_num = int(thread_num)

    def set_feature_dim(self, dim: int):
        self._dim = int(dim)

    def _require_dim(self):
        if self._dim is None:
            raise ValueError(
                "feature_dim not set: call init(feature_dim=...) or "
                "set_feature_dim() (records are label + dim floats)")


class InMemoryDataset(_DatasetBase):
    """Load-then-shuffle dataset (reference: data_set.h InMemoryDataset).

    Flow: init -> set_filelist -> load_into_memory -> [local|global]_shuffle
    -> iterate batches (of this trainer's shard after a global shuffle).
    """

    def __init__(self):
        super().__init__()
        self._feats: Optional[np.ndarray] = None
        self._labels: Optional[np.ndarray] = None
        self._order: Optional[np.ndarray] = None
        self._sharded = False
        self._epoch_seed = 0

    # -- loading -------------------------------------------------------------
    def load_into_memory(self):
        """Parse every file with n_threads native readers (reference:
        DatasetImpl::LoadIntoMemory's thread-per-channel parse)."""
        self._require_dim()
        if not self._filelist:
            raise ValueError("set_filelist before load_into_memory")
        from ..native import TextSlotDataFeed, available
        feats, labels = [], []
        if available():
            feed = TextSlotDataFeed(
                self._filelist, batch_size=4096, dim=self._dim,
                n_threads=self._thread_num, binary=self._binary)
            for f, l in feed:
                feats.append(f)
                labels.append(l)
        else:  # pure-Python fallback (same text/binary formats)
            for path in self._filelist:
                f, l = _parse_file_py(path, self._dim, self._binary)
                feats.append(f)
                labels.append(l)
        self._feats = (np.concatenate(feats) if feats else
                       np.zeros((0, self._dim), np.float32))
        self._labels = (np.concatenate(labels) if labels else
                        np.zeros((0,), np.int64))
        self._order = np.arange(len(self._labels))
        self._sharded = False

    def preload_into_memory(self):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    # -- shuffling -----------------------------------------------------------
    def local_shuffle(self, seed: Optional[int] = None):
        """Shuffle this trainer's in-memory samples only."""
        self._check_loaded()
        rng = np.random.RandomState(self._next_seed(seed))
        rng.shuffle(self._order)

    def global_shuffle(self, fleet=None, thread_num: Optional[int] = None,
                       seed: Optional[int] = None):
        """Globally shuffle + shard across trainers.  Every rank computes
        the identical seeded permutation and keeps its strided slice, so
        the union over ranks is exactly one globally-shuffled epoch (the
        reference ships samples through the PS to achieve the same).

        The permutation is applied over a CONTENT-CANONICAL ordering, not
        load order: multithreaded native loading interleaves batches
        nondeterministically per process, and a permutation of raw
        positions would then pick different samples per rank.  Sorting
        rows lexicographically first makes every rank agree (duplicate
        rows are interchangeable by construction)."""
        self._check_loaded()
        canon = np.lexsort(
            tuple(self._feats[:, d] for d in range(self._feats.shape[1]))
            + (self._labels,))
        rng = np.random.RandomState(self._next_seed(seed))
        perm = rng.permutation(len(self._labels))
        rank, nranks = self._rank_info(fleet)
        self._order = canon[perm][rank::nranks]
        self._sharded = True

    def _next_seed(self, seed):
        if seed is not None:
            return int(seed)
        self._epoch_seed += 1
        return self._epoch_seed

    @staticmethod
    def _rank_info(fleet):
        if fleet is not None and hasattr(fleet, "worker_index"):
            return int(fleet.worker_index()), max(
                1, int(fleet.worker_num()))
        from .env import get_rank, get_world_size
        return get_rank(), max(1, get_world_size())

    # -- memory management ----------------------------------------------------
    def release_memory(self):
        self._feats = self._labels = self._order = None

    def get_memory_data_size(self) -> int:
        return 0 if self._labels is None else int(len(self._labels))

    def get_shuffle_data_size(self) -> int:
        return 0 if self._order is None else int(len(self._order))

    def _check_loaded(self):
        if self._feats is None:
            raise RuntimeError("load_into_memory first")

    # -- iteration ------------------------------------------------------------
    def __iter__(self):
        self._check_loaded()
        bs = self._batch_size
        order = self._order
        for i in range(0, len(order), bs):
            idx = order[i:i + bs]
            if len(idx) < bs and self._drop_last:
                return
            yield self._feats[idx], self._labels[idx]

    def __len__(self):
        n = self.get_shuffle_data_size()
        if self._drop_last:
            return n // self._batch_size
        return (n + self._batch_size - 1) // self._batch_size


class QueueDataset(_DatasetBase):
    """Streaming single-pass dataset (reference: data_set.h QueueDataset):
    batches come straight off the native threaded feed, nothing is held
    in memory; each iteration re-opens the files."""

    def __iter__(self):
        self._require_dim()
        if not self._filelist:
            raise ValueError("set_filelist before iterating")
        from ..native import TextSlotDataFeed, available
        if available():
            feed = TextSlotDataFeed(
                self._filelist, batch_size=self._batch_size, dim=self._dim,
                n_threads=self._thread_num, binary=self._binary,
                drop_last=self._drop_last)
            yield from feed
            return
        # python fallback: parse one file at a time, carrying only the
        # partial-batch remainder across files (memory stays ~one file)
        rem_f = np.zeros((0, self._dim), np.float32)
        rem_l = np.zeros((0,), np.int64)
        for path in self._filelist:
            f, l = _parse_file_py(path, self._dim, self._binary)
            f = np.concatenate([rem_f, f])
            l = np.concatenate([rem_l, l])
            full = (len(l) // self._batch_size) * self._batch_size
            for i in range(0, full, self._batch_size):
                yield (f[i:i + self._batch_size],
                       l[i:i + self._batch_size])
            rem_f, rem_l = f[full:], l[full:]
        if len(rem_l) and not self._drop_last:
            yield rem_f, rem_l
