"""Collective communication API.

Reference: python/paddle/distributed/collective.py:38-455 (all_reduce/
broadcast/all_gather/scatter/barrier over the c_* collective ops,
operators/collective/c_allreduce_op.h:109 → ncclAllReduce).

TPU-native: collectives are XLA ops (`lax.psum/all_gather/ppermute/...`)
scheduled by the compiler over ICI — no comm streams, no ring-id bootstrap.
Two regimes:

- **Inside `shard_map`/`pmap`** (an SPMD region with a named axis): the calls
  lower to real XLA collectives on that axis.  This is the moral equivalent
  of the reference's per-rank subprocess running a c_allreduce op.
- **Eager, single controller**: arrays are either replicated (collective is
  the identity) or sharded (use `parallel` APIs / jit shardings instead), so
  the eager fallbacks implement the degenerate world-size-1 semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, wrap, unwrap


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _in_spmd(axis_name) -> bool:
    """True when tracing inside shard_map/pmap with this named axis bound."""
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def _reduce(x, op, axis_name):
    if op in (ReduceOp.SUM, "sum"):
        return lax.psum(x, axis_name)
    if op in (ReduceOp.MAX, "max"):
        return lax.pmax(x, axis_name)
    if op in (ReduceOp.MIN, "min"):
        return lax.pmin(x, axis_name)
    if op in (ReduceOp.AVG, "avg"):
        return lax.pmean(x, axis_name)
    if op in (ReduceOp.PROD, "prod"):
        # exp(psum(log|x|)) with sign parity + zero handling (a bare
        # log(x) would NaN on negatives and -inf on zeros)
        absx = jnp.abs(x)
        zero = absx == 0
        logsum = lax.psum(jnp.where(zero, 0.0, jnp.log(jnp.where(
            zero, 1.0, absx))), axis_name)
        n_neg = lax.psum((x < 0).astype(jnp.int32), axis_name)
        any_zero = lax.pmax(zero.astype(jnp.int32), axis_name)
        sign = 1.0 - 2.0 * (n_neg % 2).astype(x.dtype)
        return jnp.where(any_zero > 0, jnp.zeros_like(x),
                         sign * jnp.exp(logsum).astype(x.dtype))
    raise ValueError(f"unknown reduce op {op!r}")


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               axis_name="dp"):
    """reference collective.py:99 (c_allreduce_sum c_allreduce_op.h:157)."""
    t = wrap(tensor)
    if _in_spmd(axis_name):
        out = _reduce(unwrap(t), op, axis_name)
        result = Tensor(out, stop_gradient=t.stop_gradient)
    else:
        result = t  # world of one: reduction is identity
    if isinstance(tensor, Tensor):
        tensor._data = result._data  # paddle mutates in place
    return result


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis_name="dp"):
    """reference collective.py:155 — gathers shards along a new leading dim
    then concatenates on axis 0 (paddle semantics)."""
    t = wrap(tensor)
    if _in_spmd(axis_name):
        gathered = lax.all_gather(unwrap(t), axis_name)  # (world, ...)
        n = gathered.shape[0]
        parts = [Tensor(gathered[i]) for i in range(n)]
    else:
        parts = [t]
    if tensor_list is not None:
        tensor_list.extend(parts)
    from ..tensor.manipulation import concat
    return concat(parts, axis=0)


def broadcast(tensor, src=0, group=None, sync_op=True, axis_name="dp"):
    """reference collective.py:38 (c_broadcast).  Masked psum — one tensor's
    worth of traffic, not an all_gather of the whole axis."""
    t = wrap(tensor)
    if _in_spmd(axis_name):
        x = unwrap(t)
        mine = lax.axis_index(axis_name) == src
        out = lax.psum(jnp.where(mine, x, jnp.zeros_like(x)), axis_name)
        result = Tensor(out, stop_gradient=t.stop_gradient)
    else:
        result = t
    if isinstance(tensor, Tensor):
        tensor._data = result._data
    return result


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           axis_name="dp"):
    """reference collective.py (c_reduce_*): SPMD form reduces everywhere
    (XLA has no single-destination reduce; all ranks hold the result)."""
    return all_reduce(tensor, op=op, group=group, axis_name=axis_name)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            axis_name="dp"):
    """reference collective.py:311 — rank i gets tensor_list[i]."""
    if _in_spmd(axis_name):
        # select this rank's slice without materializing the full stack in
        # the compiled program more than once (XLA DCEs the unused rows)
        idx = lax.axis_index(axis_name)
        stacked = jnp.stack([unwrap(wrap(t)) for t in tensor_list])
        out = Tensor(lax.dynamic_index_in_dim(stacked, idx, 0,
                                              keepdims=False))
    else:
        out = wrap(tensor_list[0] if tensor_list else tensor)
    if isinstance(tensor, Tensor):
        tensor._data = out._data
    return out


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True, axis_name="dp"):
    """Sharded-sum: each rank gets its slice of the summed tensor."""
    inp = tensor_or_tensor_list
    if isinstance(inp, (list, tuple)):
        from ..tensor.manipulation import concat
        inp = concat([wrap(t) for t in inp], axis=0)
    t = wrap(inp)
    if _in_spmd(axis_name):
        out = lax.psum_scatter(unwrap(t), axis_name, scatter_dimension=0,
                               tiled=True)
        result = Tensor(out)
    else:
        result = t
    if isinstance(tensor, Tensor):
        tensor._data = result._data
    return result


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True,
             axis_name="dp"):
    """reference collective.py alltoall — rank r sends in[i] to rank i."""
    stacked = jnp.stack([unwrap(wrap(t)) for t in in_tensor_list])
    if _in_spmd(axis_name):
        out = lax.all_to_all(stacked, axis_name, split_axis=0, concat_axis=0,
                             tiled=False)
    else:
        out = stacked
    parts = [Tensor(out[i]) for i in range(out.shape[0])]
    if out_tensor_list is not None:
        out_tensor_list.extend(parts)
    return parts


def send(tensor, dst=0, group=None, sync_op=True, axis_name="dp"):
    """p2p send (reference send_v2).  SPMD programs are single-program: an
    absolute-rank send only makes sense as part of a permutation every rank
    participates in.  Use `ppermute(t, shift=...)` for the ring pattern
    (pipeline handoff) or `p2p(t, pairs=[(src, dst), ...])` for explicit
    pairs; a bare eager send in a world of one is a no-op."""
    if _in_spmd(axis_name):
        raise NotImplementedError(
            "absolute-rank send() cannot be expressed in an SPMD program — "
            "use paddle_tpu.distributed.ppermute(shift=...) for ring "
            "patterns or p2p(pairs=[(src, dst)]) for explicit pairs")
    return wrap(tensor)


def recv(tensor, src=0, group=None, sync_op=True, axis_name="dp"):
    """p2p recv (reference recv_v2) — see send()."""
    if _in_spmd(axis_name):
        raise NotImplementedError(
            "absolute-rank recv() cannot be expressed in an SPMD program — "
            "use paddle_tpu.distributed.ppermute(shift=...) for ring "
            "patterns or p2p(pairs=[(src, dst)]) for explicit pairs")
    return wrap(tensor)


def p2p(tensor, pairs, axis_name="dp"):
    """Explicit point-to-point permutation: rank src sends its tensor to
    rank dst for every (src, dst) in `pairs`; ranks not named as a dst
    receive zeros (lax.ppermute semantics)."""
    t = wrap(tensor)
    if not _in_spmd(axis_name):
        return t
    return Tensor(lax.ppermute(unwrap(t), axis_name, list(pairs)))


def ppermute(tensor, perm=None, shift=1, axis_name="dp"):
    """Ring shift (lax.ppermute): rank i -> rank (i+shift) % world."""
    t = wrap(tensor)
    if not _in_spmd(axis_name):
        return t
    n = lax.psum(1, axis_name)
    if perm is None:
        perm = [(i, (i + shift) % n) for i in range(n)]
    return Tensor(lax.ppermute(unwrap(t), axis_name, perm))


def barrier(group=None, axis_name="dp"):
    """reference collective.py:455 (barrier op / gloo barrier): XLA programs
    are compiler-scheduled so an explicit barrier is only meaningful across
    processes — use a tiny psum as the synchronization token."""
    if _in_spmd(axis_name):
        lax.psum(jnp.zeros((), jnp.int32), axis_name)
    elif jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu.barrier")


def get_rank_in_spmd(axis_name="dp"):
    return lax.axis_index(axis_name)


def get_world_size_in_spmd(axis_name="dp"):
    return lax.psum(1, axis_name)
