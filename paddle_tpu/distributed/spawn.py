"""paddle.distributed.spawn equivalent (reference: python/paddle/distributed/
spawn.py:238 — forks nprocs workers, sets trainer env, joins with error
propagation)."""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback


def _worker(fn, rank, nprocs, port, args, errq):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_COORDINATOR"] = f"127.0.0.1:{port}"
    os.environ["PADDLE_TRAINER_ENDPOINTS"] = ",".join(
        f"127.0.0.1:{port + i}" for i in range(nprocs))
    os.environ["PADDLE_CURRENT_ENDPOINT"] = f"127.0.0.1:{port + rank}"
    try:
        fn(*args)
    except Exception:
        errq.put((rank, traceback.format_exc()))
        raise


def spawn(func, args=(), nprocs=1, join=True, daemon=False, port=23456,
          **options):
    ctx = mp.get_context("spawn")
    errq = ctx.Queue()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, port, args, errq),
                        daemon=daemon)
        p.start()
        procs.append(p)

    class Context:
        processes = procs

        def join(self, timeout=None):
            """Join all workers; if any dies non-zero, terminate the rest
            (they may be blocked in a collective waiting on the dead rank —
            reference spawn.py tears the pod down the same way)."""
            import time
            deadline = None if timeout is None else time.time() + timeout
            while True:
                alive = [p for p in procs if p.is_alive()]
                failed = [p for p in procs
                          if not p.is_alive() and p.exitcode not in (0, None)]
                if failed:
                    for p in alive:
                        p.terminate()
                    for p in alive:
                        p.join(5)
                    break
                if not alive:
                    break
                if deadline is not None and time.time() > deadline:
                    break
                time.sleep(0.2)
            if not errq.empty():
                rank, tb = errq.get()
                raise RuntimeError(f"worker {rank} failed:\n{tb}")
            bad = [p.exitcode for p in procs
                   if p.exitcode not in (0, None)]
            if bad:
                raise RuntimeError(
                    f"worker process(es) exited with codes {bad}")

    c = Context()
    if join:
        c.join()
    return c
