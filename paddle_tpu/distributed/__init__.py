"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Collectives over XLA (collective.py), mesh-based parallel env (env.py),
fleet facade (fleet/), launch CLI (launch.py), spawn (spawn.py).
"""
from .env import (ParallelEnv, init_parallel_env, get_rank,  # noqa: F401
                  get_world_size, is_initialized)
from .collective import (ReduceOp, all_reduce, all_gather,  # noqa: F401
                         broadcast, reduce, scatter, reduce_scatter,
                         alltoall, send, recv, ppermute, p2p, barrier)
from .parallel_layer import DataParallel  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset  # noqa: F401


def prepare_context(strategy=None):
    """fluid dygraph parallel prepare_context: environment bootstrap for
    DataParallel (reference dygraph/parallel.py).  jax.distributed handles
    process wiring here; returns the strategy for API compat."""
    from . import env as _env
    if _env.get_world_size() > 1:
        _env.init_parallel_env()
    return strategy
