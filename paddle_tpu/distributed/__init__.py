"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Built out in paddle_tpu/distributed/*: mesh-based parallel env, collective
API over XLA collectives, fleet facade, launch CLI.
"""
import os


def get_rank():
    import jax
    try:
        return jax.process_index()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))


def get_world_size():
    import jax
    try:
        return jax.process_count()
    except Exception:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
