"""fleet.utils — filesystem clients + small helpers.

Reference: python/paddle/distributed/fleet/utils/fs.py (LocalFS/HDFSClient)
and utils/__init__.py (UtilBase: all_reduce/barrier over trainers + fs).
The clients themselves live in paddle_tpu.io.fs; this module is the
fleet-facing surface.
"""
from __future__ import annotations

import numpy as np

from ...io.fs import (LocalFS, HDFSClient, get_fs, ExecuteError,  # noqa: F401
                      FSFileExistsError, FSFileNotExistsError, FSTimeOut)
from .. import collective as _collective

__all__ = ["LocalFS", "HDFSClient", "get_fs", "UtilBase",
           "ExecuteError", "FSFileExistsError", "FSFileNotExistsError",
           "FSTimeOut"]


class UtilBase:
    """Cross-trainer helpers (reference util_factory.py UtilBase)."""

    def __init__(self, fs=None):
        self._fs = fs or LocalFS()

    def set_file_system(self, fs):
        self._fs = fs

    def get_file_shard(self, files):
        """Split a file list across trainers (util_factory.py
        get_file_shard): trainer i takes files[i::n]."""
        from ..env import get_rank, get_world_size
        n = max(1, get_world_size())
        return list(files)[get_rank() % n::n]

    def all_reduce(self, input, mode="sum"):
        import paddle_tpu as paddle
        t = paddle.to_tensor(np.asarray(input))
        _collective.all_reduce(t, op=mode)
        return np.asarray(t.numpy())

    def barrier(self):
        _collective.barrier()

    def print_on_rank(self, message, rank_id=0):
        from ..env import get_rank
        if get_rank() == rank_id:
            print(message)
