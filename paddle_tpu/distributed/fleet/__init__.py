"""Fleet — the distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py:62,129,583,978
(fleet.init → RoleMaker env parse + rendezvous; distributed_optimizer wraps
the inner optimizer; minimize ranks + applies meta-optimizers that rewrite
the program).

TPU-native: init resolves the mesh from DistributedStrategy + device count
(replacing RoleMaker ring building), distributed_optimizer returns a wrapper
whose `minimize`/`step` work eagerly for API parity, and the strategy's real
effect is on `fleet.train_step(...)` / parallel.ShardedTrainStep — sharding
specs instead of program rewriting.
"""
from __future__ import annotations

from typing import Optional

import jax

from ...parallel import (DistributedStrategy, create_mesh, set_mesh,
                         get_mesh, ShardedTrainStep)
from ..env import ParallelEnv, init_parallel_env, get_rank, get_world_size
from .. import collective as _collective

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


class UserDefinedRoleMaker:
    """compat shim (reference role_maker.py) — env-var driven."""

    def __init__(self, is_collective=True, **kw):
        self._is_collective = is_collective


PaddleCloudRoleMaker = UserDefinedRoleMaker


def init(role_maker=None, is_collective=True, strategy=None):
    """fleet.init (fleet_base.py:129)."""
    global _fleet_initialized, _strategy
    _strategy = strategy or DistributedStrategy()
    init_parallel_env()
    n = len(jax.devices())
    axes = _strategy.mesh_axes(n)
    set_mesh(create_mesh(axes))
    _fleet_initialized = True


def is_first_worker() -> bool:
    return get_rank() == 0


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def barrier_worker():
    _collective.barrier()


class DistributedOptimizer:
    """fleet.distributed_optimizer result: wraps the user optimizer.

    Eager use (API parity): behaves exactly like the inner optimizer.
    The strategy is consumed when a compiled step is built via
    fleet.distributed_train_step / parallel.ShardedTrainStep.
    """

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameters,
                                    no_grad_set)

    def step(self):
        return self._inner.step()

    def clear_grad(self):
        return self._inner.clear_grad()


def distributed_optimizer(optimizer, strategy=None) -> DistributedOptimizer:
    """fleet_base.py:583."""
    global _strategy
    st = strategy or _strategy or DistributedStrategy()
    _strategy = st
    return DistributedOptimizer(optimizer, st)


def distributed_model(model):
    """fleet_base.py distributed_model: dygraph DDP wrap."""
    from ..parallel_layer import DataParallel
    return DataParallel(model)


def distributed_train_step(model, loss_fn, optimizer, strategy=None):
    """Build the compiled SPMD train step for the current fleet mesh —
    the TPU-native 'minimize': where the reference rewrites programs, we
    hand back one jitted step with sharded params/opt/batch.  The localsgd
    strategy flag selects the divergent-replica LocalSGDTrainStep
    (localsgd_optimizer.py equivalent)."""
    st = strategy or _strategy or DistributedStrategy()
    inner = getattr(optimizer, "_inner", optimizer)
    mesh = get_mesh(create_default=True)
    if st.localsgd:
        if (st.sharding or st.tensor_parallel or st.sequence_parallel
                or st.pipeline or st.gradient_merge or st.recompute
                or st.fp16_allreduce):
            raise ValueError(
                "localsgd composes with plain DP (+AMP) only — disable "
                "sharding/tensor_parallel/sequence_parallel/pipeline/"
                "gradient_merge/recompute/fp16_allreduce")
        from ...parallel.localsgd import LocalSGDTrainStep
        k = (st.localsgd_configs or {}).get("k_steps", 4)
        return LocalSGDTrainStep(
            model, loss_fn, inner, k_steps=k, mesh=mesh,
            amp_level=("O1" if st.amp else None),
            amp_dtype=st.amp_configs.dtype)
    return ShardedTrainStep(model, loss_fn, inner, strategy=st, mesh=mesh)


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy
